"""Streaming incremental re-scoring (BASELINE configs[4]).

Steady-state path for full-mix churn at ~1k events/sec: the resident device
state is the feature matrix PLUS the dense evidence tables
(ev_idx/ev_cnt/ev_pair_slot). Every mutation kind — feature drift, pod
reschedule, node/edge creation and deletion, incident arrival and closure —
reduces to two padded scatter deltas applied inside ONE fused device call
per tick:

* feature delta: [K, DIM] rows scattered into the feature matrix;
* row delta: [Kr, W] evidence-table rows (slots, counts, pair ids) for the
  incident rows whose evidence set changed.

The host keeps the authoritative per-incident evidence lists and per-row
pair maps (node -> row-local pair id for multiple_pods_same_node), so
structural churn is O(change) bookkeeping + a bounded scatter — the
Neo4j-MERGE absorption story (reference neo4j.py:95-166) without ever
rebuilding or re-uploading the snapshot. Free slots come from the padding
the buckets already carry: new nodes take free feature rows, new incidents
take free incident rows, new evidence appends into slot slack. Only bucket
overflow (feature rows, incident rows, slot width) falls back to a full
snapshot rebuild — counted in stats so benches can prove it stays rare.
"""
from __future__ import annotations

import collections
import threading
import time
import weakref
from functools import partial
from typing import Iterable

import numpy as np

import jax
import jax.numpy as jnp

from ..config import Settings, get_settings
from ..observability import get_logger
from ..observability import metrics as obs_metrics
from ..observability import scope as obs_scope
from ..graph.schema import EntityKind, RelationKind
from ..graph.snapshot import GraphSnapshot, build_snapshot, extract_node_features
from ..graph.store import EvidenceGraphStore
from ..utils.padding import bucket_for
from .tpu_backend import _PAIR_WIDTH_BUCKETS, _WIDTH_BUCKETS

# graft-tide appended the 65536 rung for 500k-pod churn bursts (the
# coalesced-tick registry entry keys its canonical shape off the top
# rung, so its cost baseline was re-derived with the stretch).
# graft-lattice: the rungs now live in the declared ladder registry
# (analysis/ladders.py) — one source of truth for serving, bench and
# the ladder-gap/divisibility checks; the aliases keep every existing
# import site working.
from ..analysis.ladders import (DELTA_BUCKETS as _DELTA_BUCKETS,
                                ROW_BUCKETS as _ROW_BUCKETS)

_NO_PAIR = -1          # host-side "evidence has no scheduled node" marker

log = get_logger("streaming")


class NonFiniteDelta(RuntimeError):
    """A staged feature delta carries NaN/inf rows. Scattering it would
    poison the donated resident state — and the rules fold absorbs NaN
    through its threshold comparisons (NaN > t is False), so the damage
    would surface as silently WRONG verdicts, not as NaN ones. Raised
    before the scatter (the pending deltas are already drained, so only a
    journal replay restages them — ``stage`` marks the state suspect for
    the shield's ladder, rca/shield.py, which quarantines the batch)."""

    stage = "dispatch"


class NeedsRebuild(Exception):
    """A growth ladder is exhausted: the next width/pair-width bucket lies
    beyond the ladder top, so in-place growth would mint an unplanned
    off-ladder compile mid-serve. Raised by ``_grow_width``/
    ``_grow_pair_width`` and caught by ``_grow``, which escalates to a
    full store-derived ``_rebuild()`` (the rebuild may legitimately land
    on an off-ladder power-of-two shape — but explicitly, store-derived,
    through the warmable rebuild path)."""


@partial(jax.jit, static_argnames=("padded_incidents", "pair_width",
                                   "pk", "rk", "width"),
         donate_argnums=(0, 3, 4, 5))
def _tick(features, ints, f_rows, ev_idx, ev_cnt, ev_pair,
          chain, padded_incidents: int, pair_width: int,
          pk: int, rk: int, width: int):
    """One fused device call per tick: scatter the padded feature delta and
    the padded evidence-row delta into the resident state, then score.
    Out-of-range indices (the padding of each delta) drop out. The caller
    replaces its state handles with the returned buffers. The resident
    state (features + the three evidence tables) is DONATED: the caller
    never reads the pre-tick buffers again, so XLA aliases the delta
    scatters in place instead of reallocating the full mirror every tick
    — at pipeline depth > 1 the un-donated variant holds depth+1 copies
    of the resident set live in HBM. Enforced by the `tick-donation`
    audit rule (analysis/ast_lint.py); warm paths must therefore pass
    stand-in buffers, never the live handles.

    All integer delta arrays arrive PACKED in one flat int32 buffer
    (f_idx | r_idx | r_cnt | r_ev | r_pair): the dev tunnel charges
    per-transfer latency, so 2 host→device transfers per tick (ints +
    f_rows) beat 6 — this alone moved the full-mix streaming bench by
    ~3 ms/tick. pk/rk/width are static, matching the bucket discipline
    (same compiled-variant count as separate padded arrays had)."""
    from .tpu_backend import _aggregate, finish_scores

    f_idx = ints[:pk]
    r_idx = ints[pk:pk + rk]
    r_cnt = ints[pk + rk:pk + 2 * rk]
    off = pk + 2 * rk
    r_ev = ints[off:off + rk * width].reshape(rk, width)
    r_pair = ints[off + rk * width:off + 2 * rk * width].reshape(rk, width)

    features = features.at[f_idx].set(f_rows, mode="drop")
    ev_idx = ev_idx.at[r_idx].set(r_ev, mode="drop")
    ev_cnt = ev_cnt.at[r_idx].set(r_cnt, mode="drop")
    ev_pair = ev_pair.at[r_idx].set(r_pair, mode="drop")
    counts, per_row_max = _aggregate(
        features, ev_idx, ev_cnt, ev_pair, padded_incidents, pair_width)
    counts = counts + jnp.minimum(chain, 0.0)[:, None]
    return (features, ev_idx, ev_cnt, ev_pair) + finish_scores(
        counts, per_row_max, padded_incidents)


def _pack_ints(f_idx, r_idx, r_cnt, r_ev, r_pair) -> np.ndarray:
    return np.concatenate([f_idx, r_idx, r_cnt, r_ev.ravel(),
                           r_pair.ravel()]).astype(np.int32, copy=False)


def _pack_ints_sharded(f_idx, r_idx, r_cnt, r_ev, r_pair) -> np.ndarray:
    """[G, L] per-shard packed delta for the graph-sharded tick
    (parallel/sharded_streaming.sharded_rules_tick): each shard's row is
    its OWN routed feature-delta indices followed by the (small, [rk])
    row-delta payload duplicated per shard — still one host→device
    transfer for every integer delta."""
    row_payload = np.concatenate(
        [r_idx, r_cnt, r_ev.ravel(), r_pair.ravel()]).astype(np.int32)
    g = f_idx.shape[0]
    return np.concatenate(
        [f_idx.astype(np.int32, copy=False),
         np.broadcast_to(row_payload, (g, row_payload.size))],
        axis=1).astype(np.int32, copy=False)


@partial(jax.jit, static_argnames=("li", "pk", "dim", "gi"))
def _delta_pack(slab, li: int, pk: int, dim: int, gi: int = 0):
    """graft-intake: split one staged int32 slab into the fused tick's
    ``(ints, f_rows)`` operands ON DEVICE. The columnar staging path
    (``_staged_delta_columnar``) assembles the whole tick delta — the
    packed integer payload AND the [pk, DIM] float feature rows (written
    through an int32 view, bit-exact) — into a single preallocated host
    slab, so each tick pays ONE host→device transfer instead of two
    (PR 1 cut 6 transfers to 2 the same way; this removes the last
    split). Zero FLOPs: a slice and an elementwise bitcast; registered as
    the ``ingest.delta_pack`` audit entrypoint with a zero-collective
    CostSpec.

    graft-fuse closes PR 11's named follow-up: with ``gi > 0`` the slab
    additionally carries the GNN tick's packed aux/edge/incident delta
    (``_packed_gnn_delta``) after the feature rows, returned as a third
    on-device slice — so the GNN streaming tick's delta rides the SAME
    host→device transfer as the base slab instead of paying its own."""
    ints = slab[:li]
    rows = jax.lax.bitcast_convert_type(
        slab[li:li + pk * dim].reshape(pk, dim), jnp.float32)
    if gi:
        return ints, rows, slab[li + pk * dim:li + pk * dim + gi]
    return ints, rows


class FeatureStage:
    """graft-intake: columnar pending-feature staging.

    Replaces the ``_pending_feat`` dict of per-row np arrays with two
    preallocated columns — ``[cap]`` int32 node rows + ``[cap, DIM]``
    float32 feature rows — plus a row→slot map for the latest-wins
    contract (an updated row overwrites its slot IN PLACE, keeping its
    original position, exactly like a dict key update). Draining into
    the tick's staged slab is then two array copies (a memcpy) instead
    of a Python loop building ``list(dict.values())`` + ``np.stack``.

    The dict surface (``keys/values/items/len/in/iter/clear/get``) is
    preserved so every existing consumer — the sharded delta router, the
    GNN tick's aux-row capture, the multi-tenant pack's heal/queue-depth
    paths, the shield's host-state pickle — works on either
    representation; insertion order is identical to the dict path, which
    is what keeps the staged buffers bit-identical to the oracle."""

    def __init__(self, dim: int, capacity: int = _DELTA_BUCKETS[0]) -> None:
        self._dim = int(dim)
        cap = max(int(capacity), 1)
        self._idx = np.empty(cap, np.int32)
        self._rows = np.empty((cap, self._dim), np.float32)
        self._slots: dict[int, int] = {}
        self._n = 0

    def _grow_cap(self) -> None:
        cap = len(self._idx) * 2
        idx = np.empty(cap, np.int32)
        rows = np.empty((cap, self._dim), np.float32)
        idx[:self._n] = self._idx[:self._n]
        rows[:self._n] = self._rows[:self._n]
        self._idx, self._rows = idx, rows

    def __setitem__(self, row: int, feats) -> None:
        s = self._slots.get(row)
        if s is None:
            if self._n == len(self._idx):
                self._grow_cap()
            s = self._n
            self._slots[row] = s
            self._idx[s] = row
            self._n += 1
        self._rows[s] = feats

    def __len__(self) -> int:
        return self._n

    def __contains__(self, row: int) -> bool:
        return row in self._slots

    def __iter__(self):
        return iter(self.keys())

    def keys(self) -> list[int]:
        return [int(r) for r in self._idx[:self._n]]

    def values(self) -> list[np.ndarray]:
        return [self._rows[s] for s in range(self._n)]

    def items(self) -> list[tuple[int, np.ndarray]]:
        return [(int(self._idx[s]), self._rows[s])
                for s in range(self._n)]

    def get(self, row: int, default=None):
        s = self._slots.get(row)
        return default if s is None else self._rows[s]

    def clear(self) -> None:
        self._slots.clear()
        self._n = 0

    def discard_range(self, lo: int, hi: int) -> int:
        """Drop every staged row in ``[lo, hi)`` (tenant quarantine,
        rca/surge.py) with one vectorized compaction; relative order of
        the surviving rows is preserved. Returns rows dropped."""
        k = self._n
        idx = self._idx[:k]
        keep = (idx < lo) | (idx >= hi)
        m = int(keep.sum())
        if m == k:
            return 0
        self._idx[:m] = idx[keep]
        self._rows[:m] = self._rows[:k][keep]
        self._n = m
        self._slots = {int(r): j for j, r in enumerate(self._idx[:m])}
        return k - m

    def drain_into(self, idx_out: np.ndarray, rows_out: np.ndarray,
                   sentinel: int) -> int:
        """The memcpy: copy the staged columns into the tick's padded
        delta views (tail = out-of-range sentinel indices + zero rows,
        bit-identical to the dict oracle's padding) and reset. Returns
        the live count."""
        k = self._n
        idx_out[:k] = self._idx[:k]
        idx_out[k:] = sentinel
        rows_out[:k] = self._rows[:k]
        rows_out[k:] = 0.0
        self._slots.clear()
        self._n = 0
        return k


class _SlabPool:
    """Rotating preallocated int32 staging slabs, keyed by length.

    ``jnp.asarray`` may alias a host buffer zero-copy on backends that
    support it, so a slab must not be rewritten while a tick that staged
    from it can still be executing; rotating ``copies`` slabs per length
    (pipeline depth + slack) bounds reuse strictly below the executor's
    maximum in-flight window."""

    def __init__(self, copies: int) -> None:
        self.copies = max(int(copies), 2)
        self._pools: dict[int, tuple[list[np.ndarray], int]] = {}

    def acquire(self, n: int) -> np.ndarray:
        slabs, nxt = self._pools.get(n, ([], 0))
        if len(slabs) < self.copies:
            slab = np.zeros(n, np.int32)
            slabs.append(slab)
            self._pools[n] = (slabs, 0)
            return slab
        self._pools[n] = (slabs, (nxt + 1) % self.copies)
        return slabs[nxt]


# Bound interpreter exit on ANY path, including scripts that use
# auto_warm_growth directly and never call app.stop()/worker.drain():
# threading._shutdown joins non-daemon threads BEFORE ordinary atexit
# hooks run, so a plain atexit hook fires too late to stop a warm combo
# sweep — threading._register_atexit callbacks run inside _shutdown
# before the join (the same hook concurrent.futures relies on). One
# module-level hook over a WeakSet, so scorer churn neither accumulates
# callbacks nor pins dead scorers.
_live_scorers: "weakref.WeakSet[StreamingScorer]" = weakref.WeakSet()
_exit_hook_installed = False


def _track_for_exit(scorer: "StreamingScorer") -> None:
    global _exit_hook_installed
    _live_scorers.add(scorer)
    if not _exit_hook_installed:
        _exit_hook_installed = True
        try:
            threading._register_atexit(_stop_all_warm)
        except RuntimeError:  # interpreter already shutting down
            pass


def _stop_all_warm() -> None:
    for s in list(_live_scorers):
        # graft-audit: allow[lock-guard] atexit stop flag — the interpreter is exiting and a bool store is atomic under the GIL; taking _warm_lock here could deadlock against a warm thread mid-step
        s._warm_stop = True


class StreamingScorer:
    """Device-resident scorer with incremental structural + feature deltas."""

    def __init__(self, store: EvidenceGraphStore,
                 settings: Settings | None = None,
                 mesh: "jax.sharding.Mesh | None" = None,
                 now_s: float | None = None) -> None:
        self.settings = settings or get_settings()
        self.store = store
        # deterministic replay clock: recency features (e.g. deploy age)
        # extract against THIS epoch instead of the wall clock when set.
        # Serving leaves it None; replay harnesses (the pipeline depth
        # sweep, the depth-parity tests) pin it so two replays of one
        # seeded script produce bit-identical feature rows.
        self.now_s = now_s
        # optional device mesh with a "dp" axis: the resident incident
        # tables shard over it (features replicated — every shard gathers
        # arbitrary global node ids), so one resident scorer serves from
        # a whole slice. GSPMD propagates the shardings through the fused
        # tick, so outputs stay sharded across ticks with zero code
        # changes in _tick; results are bit-identical to single-device
        # (tests/test_streaming.py). Falls back to unsharded placement if
        # the incident bucket is not divisible by the dp axis.
        # graft-fleet: with settings.serve_graph_shards > 1 and no
        # explicit mesh, the scorer builds its own (1 x D) serving mesh —
        # the resident state shards into D graph partitions and every
        # tick runs the mesh-resident sharded variant
        # (parallel/sharded_streaming.py).
        if mesh is None:
            shards = int(getattr(self.settings, "serve_graph_shards", 1))
            if shards > 1:
                from ..parallel.mesh import serving_mesh
                mesh = serving_mesh(shards)
                if mesh is None:
                    # surface the fallback — an operator asking for a
                    # sharded fleet must not silently get one chip
                    log.warning("serve_graph_shards_unavailable",
                                requested=shards,
                                devices=len(jax.devices()))
        self.mesh = mesh
        self.rebuilds = 0
        self.syncs = 0
        self.fetches = 0
        # device scoring passes actually enqueued (dispatch() calls) —
        # the denominator of the graft-surge batching story: N concurrent
        # incidents served per pass means fewer dispatches, and the A/B
        # bench and the perf_contract tests count exactly this
        self.dispatches = 0
        # opt-in (the worker sets it): every shape change re-warms the
        # next bucket shapes on a background thread. _warm_lock guards the
        # active/pending/stop flags (see _rearm_warm_growth).
        self.auto_warm_growth = False
        self._warm_lock = threading.Lock()
        self._warm_thread: threading.Thread | None = None
        self._warm_active = False
        self._warm_rearm_pending = False
        self._warm_stop = False
        _track_for_exit(self)
        # serializes sync()+dispatch() for multi-threaded serving (workflow
        # steps run on executor threads); single-threaded benches skip it
        self.serve_lock = threading.Lock()
        # pipelined serving executor (graft-pipeline): a bounded queue of
        # dispatched-but-unfetched tick results. tick_async() overlaps the
        # host's delta-packing of tick t+1 with device execution of tick t
        # and never blocks while a slot is free; a full queue coalesces
        # pending deltas into one larger tick (bounded by the top of the
        # _DELTA_BUCKETS ladder) instead of queueing unboundedly. Results
        # are only ever fetched at the caller boundary — rescore()/serve()
        # fetch the NEWEST tick once and drop superseded results unfetched.
        self.pipeline_depth = max(1, int(getattr(
            self.settings, "serve_pipeline_depth", 2)))
        # graft-intake: rotating device-ready staging slabs for the
        # columnar delta pack (one int32 buffer per tick = ints + bitcast
        # feature rows). Sized strictly above the executor's maximum
        # in-flight window so a slab is never rewritten under a tick that
        # staged from it.
        self._stage_pool = _SlabPool(self.pipeline_depth + 3)
        # graft-fuse: the on-device slice of the GNN delta when it rode
        # the staged slab (set per dispatch by the columnar path, read
        # and cleared by GnnStreamingScorer.dispatch)
        self._staged_gnn_dev = None
        self._inflight: collections.deque = collections.deque()
        self._coalesce_bound = _DELTA_BUCKETS[-1]
        self.coalesced_ticks = 0
        self.deferred_fetches = 0
        self.stall_seconds = 0.0
        # graft-storm: absorb() busy-yield accounting + the backlog bound
        # past which a yield escalates to a synchronous drain; storm-mode
        # ticks also coalesce harder (see _tick_async_locked)
        self.absorb_busy = 0
        self.absorb_sync_drains = 0
        self.storm_coalesced_ticks = 0
        self._max_journal_backlog = max(int(getattr(
            self.settings, "ingest_max_journal_backlog", 8192)), 1)
        # graft-scope: per-tick telemetry front-end. The hot path pays one
        # attribute read per boundary when disabled; enabled it records
        # host-monotonic stage marks only — no device syncs the serving
        # path would not already pay, no jitted code touched.
        # _inflight_meta shadows _inflight one TickSpan per queued tick
        # (None when telemetry is off) so device completion is stamped at
        # the moment the HOST first observes the donated tick's ready
        # event — retire, stall, or fetch, whichever comes first.
        self.scope = obs_scope.TickScope(backend="rules",
                                         settings=self.settings)
        self._scope_tier = "steady"        # the shield re-stamps on ladder moves
        self._inflight_meta: collections.deque = collections.deque()
        self._last_tick_span = None
        self._scope_coalesced_since = 0
        self._scope_key: tuple = ()
        self._scope_entry = "streaming.rules_tick"
        # graft-swell: the owning serving pack's id (SurgeServer stamps
        # the pack index when it builds a fleet) — labels the per-scorer
        # pipeline/roofline gauges so N packs don't alias into one series
        self._scope_pack = "0"
        # coalesced-serving state (see serve()): one device pass satisfies
        # every caller whose store writes preceded that pass's sync
        self._serve_cv = threading.Condition()
        self._serve_next_gen = 1
        self._serve_done_gen = 0
        self._serve_ticking = False
        self._serve_result: dict | None = None
        # graft-evolve: the params generation this scorer serves (0 = the
        # offline checkpoint; the rules fold has no learned params so the
        # base scorer never advances it). GnnStreamingScorer's hot
        # checkpoint swap bumps it at a queue generation boundary; every
        # TickSpan and verdict dict carries the generation that actually
        # produced it, so a swap is auditable tick by tick.
        self.params_generation = 0
        # graft-shield seam: when a FaultInjector (rca/faults.py) is
        # attached, the tick pipeline consults it at each named stage —
        # None (the default) costs one attribute read per hook. The
        # ShieldedScorer flips finite_delta_guard on when it wraps this
        # scorer: staged feature rows are isfinite-checked (O(delta))
        # before they scatter into the donated state.
        self.fault_injector = None
        self.finite_delta_guard = False
        self._init_from_store()

    # -- (re)initialisation ------------------------------------------------

    def _drop_stale_inflight(self) -> None:
        """A rebuild supersedes every in-flight tick result (and their
        buffers carry the OLD shapes): drop them unfetched. Shared with
        the multi-tenant pack rebuild (rca/surge.py)."""
        stale = getattr(self, "_inflight", None)
        if stale:
            self.deferred_fetches += len(stale)
            obs_metrics.SERVE_DEFERRED_FETCHES.inc(float(len(stale)))
            stale.clear()
        stale_meta = getattr(self, "_inflight_meta", None)
        if stale_meta:
            for sp in stale_meta:
                self.scope.finalize(sp)
            stale_meta.clear()

    def _init_from_store(self) -> None:
        """Tensorize the store and derive the host-authoritative incremental
        state. Called at construction and on bucket-overflow rebuilds.
        Buckets are picked with 1/3 growth slack so structural churn lands
        in free padded rows instead of forcing mid-stream rebuilds."""
        self._drop_stale_inflight()
        # capture the journal cursor BEFORE tensorizing: mutations landing
        # in between are both in the snapshot and replayed by the next
        # sync(), and every mirror op is an idempotent MERGE, so replays
        # are safe while missed records would not be
        self._synced_seq = self.store.journal_seq
        snap = build_snapshot(self.store, self.settings, slack=1 / 3,
                              now_s=self.now_s)
        self.snapshot: GraphSnapshot = snap
        pn, pi = snap.padded_nodes, snap.padded_incidents

        # node rows
        self._node_ids: list[str | None] = list(snap.node_ids) + [None] * (
            pn - snap.num_nodes)
        self._id_to_idx: dict[str, int] = {
            nid: i for i, nid in enumerate(snap.node_ids)}
        self._free_node_rows: list[int] = list(
            range(pn - 1, snap.num_nodes - 1, -1))

        # incident rows
        self._inc_row_of: dict[str, int] = {
            iid: r for r, iid in enumerate(snap.incident_ids)}
        self._row_inc: list[str | None] = list(snap.incident_ids) + [None] * (
            pi - snap.num_incidents)
        self._free_inc_rows: list[int] = list(
            range(pi - 1, snap.num_incidents - 1, -1))

        # pod -> scheduled node (for pair ids of new/retargeted evidence),
        # plus the reverse index node -> pods so entity removal finds its
        # stranded pods in O(degree) instead of scanning every pod
        self._pod_node: dict[int, int] = {}
        self._sched_pods: dict[int, set[int]] = {}
        live = snap.edge_mask > 0
        sched = live & (snap.edge_rel == int(RelationKind.SCHEDULED_ON))
        for pos in np.nonzero(sched)[0]:
            s, d = int(snap.edge_src[pos]), int(snap.edge_dst[pos])
            pod, node = (s, d) if snap.node_kind[s] == int(EntityKind.POD) else (d, s)
            self._set_pod_node(pod, node)

        # per-incident evidence lists + pair maps (authoritative host state)
        is_ev = live & ((snap.edge_rel == int(RelationKind.AFFECTS))
                        | (snap.edge_rel == int(RelationKind.CORRELATES_WITH)))
        inc_row = np.full(pn, -1, dtype=np.int64)
        real = snap.incident_mask > 0
        inc_row[snap.incident_nodes[real]] = np.arange(int(real.sum()))
        self._row_nodes: list[list[int]] = [[] for _ in range(pi)]
        self._row_pairs: list[list[int]] = [[] for _ in range(pi)]
        self._pair_map: list[dict[int, int]] = [{} for _ in range(pi)]
        self._ev_rows_of_node: dict[int, set[int]] = {}
        for pos in np.nonzero(is_ev)[0]:
            r = int(inc_row[snap.edge_src[pos]])
            if r < 0:
                continue  # undirected duplicate (dst is the incident)
            dst = int(snap.edge_dst[pos])
            self._append_evidence_host(r, dst)

        # static shapes (width also carries 1/3 slack: appended evidence
        # must not cross a width bucket right away); _rebuild_widths is the
        # single source of this derivation so warm_growth pre-compiles the
        # shapes a rebuild will actually land on
        self.width, self.pair_width = self._rebuild_widths()

        # device state
        self._features_dev = jnp.asarray(snap.features)
        ev_idx, ev_cnt, ev_pair = self._materialize_rows(range(pi))
        self._ev_idx_dev = jnp.asarray(ev_idx)
        self._ev_cnt_dev = jnp.asarray(ev_cnt)
        self._pair_dev = jnp.asarray(ev_pair)
        # dispatch always scores with a zero chain; cache it device-side so
        # ticks don't pay a fresh host→device transfer for a constant
        self._chain0 = jnp.zeros((pi,), jnp.float32)
        self._apply_sharding()

        # pending deltas. The feature delta is keyed by node row so the
        # LATEST update per row wins: XLA scatter-set order for duplicate
        # indices is unspecified, so a remove-then-reuse of the same row
        # within one tick must collapse to one entry (ADVICE r2).
        # graft-intake: with settings.ingest_columnar the dict of per-row
        # arrays becomes a FeatureStage — preallocated columnar buffers
        # whose drain is a memcpy into the device-ready staged slab; the
        # dict path stays as the bit-parity oracle.
        if getattr(self.settings, "ingest_columnar", False):
            self._pending_feat: "dict[int, np.ndarray] | FeatureStage" = \
                FeatureStage(snap.features.shape[1])
        else:
            self._pending_feat = {}
        self._dirty_rows: set[int] = set()

    # -- slot-space seams (graft-surge) ------------------------------------
    #
    # The multi-tenant pack (rca/surge.py) carves the node/incident slot
    # spaces into per-tenant regions: allocation must stay inside the id's
    # region and store lookups must resolve through the id's tenant store.
    # The base scorer serves ONE store, so these default to the single
    # free lists / the single store — zero behavior change.

    def _node_row_available(self, node_id: str) -> bool:
        return bool(self._free_node_rows)

    def _take_node_row(self, node_id: str) -> int:
        return self._free_node_rows.pop()

    def _put_node_row(self, row: int) -> None:
        self._free_node_rows.append(row)

    def _inc_row_available(self, node_id: str) -> bool:
        return bool(self._free_inc_rows)

    def _take_inc_row(self, node_id: str) -> int:
        return self._free_inc_rows.pop()

    def _put_inc_row(self, row: int) -> None:
        self._free_inc_rows.append(row)

    def _store_node(self, node_id: str):
        """The live store node behind a (possibly tenant-namespaced) id."""
        return self.store._nodes.get(node_id)

    def _canon_incident_id(self, incident_node_id: str) -> str:
        """Canonical incident node id: bare uuids gain the ``incident:``
        prefix. The multi-tenant pack overrides this — its journal-driven
        ids arrive already canonical and namespaced."""
        return incident_node_id if incident_node_id.startswith("incident:") \
            else f"incident:{incident_node_id}"

    def _tenant_count(self) -> int:
        """Tenants packed onto this resident state (1 for the base
        scorer); labels the per-pass incident-batch histogram."""
        return 1

    def serving_node_id(self, node_id: str, tenant: str = "default") -> str:
        """Translate a store-local node id into this scorer's slot-space
        id (the multi-tenant pack namespaces per tenant)."""
        return node_id

    def _sharded(self, pi: int) -> bool:
        """True when `pi` incident rows can shard over the mesh's dp axis."""
        return (self.mesh is not None
                and pi % self.mesh.shape["dp"] == 0)

    def _graph_size(self) -> int:
        if self.mesh is None or "graph" not in self.mesh.axis_names:
            return 1
        return self.mesh.shape["graph"]

    def _graph_sharded(self, pn: int, pi: int) -> bool:
        """True when the mesh carries a real ``graph`` axis AND both the
        node and incident buckets divide over it — the (dp × graph)
        serving mode with features split into node blocks (ring tick)."""
        g = self._graph_size()
        return g > 1 and pn % g == 0 and self._sharded(pi)

    def _shardings(self, pn: int | None = None, pi: int | None = None):
        """(features, [Pi] rows, [Pi, W] tables) NamedShardings for state
        at shape (pn, pi) (default: current). Features are P("graph") in
        graph mode — split node blocks — and replicated otherwise."""
        from jax.sharding import NamedSharding, PartitionSpec as P
        m = self.mesh
        if pn is None:
            pn = self.snapshot.padded_nodes
        if pi is None:
            pi = self.snapshot.padded_incidents
        feat = P("graph") if self._graph_sharded(pn, pi) else P()
        return (NamedSharding(m, feat),
                NamedSharding(m, P("dp")),        # [Pi] row vectors
                NamedSharding(m, P("dp", None)))  # [Pi, W] row tables

    def _tick_fn(self, pn: int, pi: int, width: int, pair_width: int,
                 pk: int, rk: int):
        """The fused tick for state at shape (pn, pi): the shard_map'd
        mesh-resident variant in (dp × graph) mode (owner-fold + one
        verdict psum, per-shard routed deltas —
        parallel/sharded_streaming.sharded_rules_tick; ``pk`` is then the
        PER-SHARD delta sub-bucket), the plain jit (GSPMD-propagated when
        dp-sharded) otherwise. Single seam so dispatch and every warm
        path compile exactly the variant serving will run."""
        if self._graph_sharded(pn, pi):
            from ..parallel.sharded_streaming import sharded_rules_tick
            g, dp = self.mesh.shape["graph"], self.mesh.shape["dp"]
            return sharded_rules_tick(self.mesh, pn // g, pi // dp,
                                      pair_width, pk, rk, width)
        return partial(_tick, padded_incidents=pi, pair_width=pair_width,
                       pk=pk, rk=rk, width=width)

    def _apply_sharding(self) -> None:
        """Place the resident state per the mesh (no-op without one).
        Called from _init_from_store and after width growths re-materialize
        tables; device_put with an unchanged sharding is free."""
        if not self._sharded(self.snapshot.padded_incidents):
            if self.mesh is not None:
                # surface the silent single-device fallback: the operator
                # configured a mesh but the bucket doesn't divide over it
                log.warning("mesh_sharding_skipped",
                            padded_incidents=self.snapshot.padded_incidents,
                            dp=self.mesh.shape["dp"])
            return
        rep, row1, row2 = self._shardings()
        self._features_dev = jax.device_put(self._features_dev, rep)
        self._ev_idx_dev = jax.device_put(self._ev_idx_dev, row2)
        self._ev_cnt_dev = jax.device_put(self._ev_cnt_dev, row1)
        self._pair_dev = jax.device_put(self._pair_dev, row2)
        self._chain0 = jax.device_put(self._chain0, row1)

    def _set_pod_node(self, pod: int, node: int) -> None:
        """Point `pod` at `node`, keeping the reverse index coherent."""
        old = self._pod_node.get(pod)
        if old == node:
            return
        if old is not None:
            s = self._sched_pods.get(old)
            if s is not None:
                s.discard(pod)
                if not s:
                    del self._sched_pods[old]
        self._pod_node[pod] = node
        self._sched_pods.setdefault(node, set()).add(pod)

    def _del_pod_node(self, pod: int) -> int | None:
        """Unmap `pod`; returns its former node (reverse index updated)."""
        node = self._pod_node.pop(pod, None)
        if node is not None:
            s = self._sched_pods.get(node)
            if s is not None:
                s.discard(pod)
                if not s:
                    del self._sched_pods[node]
        return node

    def _append_evidence_host(self, r: int, dst: int) -> None:
        """Host bookkeeping for one evidence slot (no width checks)."""
        self._row_nodes[r].append(dst)
        node = self._pod_node.get(dst)
        if node is None:
            self._row_pairs[r].append(_NO_PAIR)
        else:
            pm = self._pair_map[r]
            pid = pm.get(node)
            if pid is None:
                # max+1, NOT len(pm): removals can leave holes, and len(pm)
                # could collide with a live pid (ADVICE r2 high). The dense
                # invariant (_recompact_pairs) makes these equal, but the
                # allocator must stay safe even mid-transition.
                pid = max(pm.values(), default=-1) + 1
                pm[node] = pid
            self._row_pairs[r].append(pid)
        self._ev_rows_of_node.setdefault(dst, set()).add(r)

    def _recompact_pairs(self, r: int) -> None:
        """Rebuild row r's pair map dense (0..K-1) from its live slots.

        Called whenever a pair key can go stale — evidence removal, entity
        removal, pod retarget — so pair ids never develop holes: every pm
        key is referenced by at least one slot and max pid == len(pm)-1.
        Without this, a popped key lets ``len(pm)`` alias a live pid and
        lets the max pid reach ``pair_width`` (the no-node sentinel),
        silently dropping a real pod from the same-node condition."""
        pm: dict[int, int] = {}
        nodes = self._row_nodes[r]
        pairs = self._row_pairs[r]
        for i, dst in enumerate(nodes):
            node = self._pod_node.get(dst)
            if node is None:
                pairs[i] = _NO_PAIR
            else:
                pairs[i] = pm.setdefault(node, len(pm))
        self._pair_map[r] = pm
        self._dirty_rows.add(r)

    def _materialize_pairs(self, rows: Iterable[int]) -> np.ndarray:
        """[K, W] pair table only (_NO_PAIR becomes the out-of-range
        sentinel == pair_width)."""
        rows = list(rows)
        ev_pair = np.full((len(rows), self.width), self.pair_width, np.int32)
        for j, r in enumerate(rows):
            pairs = np.asarray(self._row_pairs[r], np.int32)
            if len(pairs):
                ev_pair[j, :len(pairs)] = np.where(
                    pairs < 0, self.pair_width, pairs)
        return ev_pair

    def _materialize_rows(self, rows: Iterable[int]
                          ) -> tuple[np.ndarray, np.ndarray, np.ndarray]:
        """[K, W] slot tables for the given incident rows from host state."""
        rows = list(rows)
        k = len(rows)
        ev_idx = np.zeros((k, self.width), np.int32)
        ev_cnt = np.zeros(k, np.int32)
        for j, r in enumerate(rows):
            nodes = self._row_nodes[r]
            ev_cnt[j] = len(nodes)
            if nodes:
                ev_idx[j, :len(nodes)] = nodes
        return ev_idx, ev_cnt, self._materialize_pairs(rows)

    # -- bucket management -------------------------------------------------

    def _grow(self, grower) -> bool:
        """Run one growth step; on ladder exhaustion (NeedsRebuild)
        escalate to a full store-derived rebuild. Returns True when the
        escalation rebuilt (callers must stop touching pre-growth rows)."""
        try:
            grower()
            return False
        except NeedsRebuild as exc:
            log.warning("growth_ladder_exhausted", error=str(exc))
            obs_metrics.SHIELD_TIER_TRANSITIONS.inc(tier="ladder_rebuild")
            self._rebuild()
            return True

    def _grow_width(self) -> None:
        """Slot-width bucket overflow: next bucket, re-ship ALL rows (new
        static shape -> new program; pays one compile in the hot loop
        unless warm(include_next_width=True) pre-compiled it). Raises
        NeedsRebuild past the ladder top (see _grow)."""
        nxt = bucket_for(self.width + 1, _WIDTH_BUCKETS)
        if nxt > _WIDTH_BUCKETS[-1]:
            raise NeedsRebuild(
                f"slot width {nxt} beyond ladder top {_WIDTH_BUCKETS[-1]}")
        self.width = nxt
        pi = self.snapshot.padded_incidents
        ev_idx, ev_cnt, ev_pair = self._materialize_rows(range(pi))
        self._ev_idx_dev = jnp.asarray(ev_idx)
        self._ev_cnt_dev = jnp.asarray(ev_cnt)
        self._pair_dev = jnp.asarray(ev_pair)
        self._dirty_rows.clear()
        self._apply_sharding()
        self._rearm_warm_growth()

    def _grow_pair_width(self) -> None:
        """Pair bucket overflow: bump the bucket and re-stamp sentinels.
        Never shrinks mid-stream (ADVICE r1: a shrunk sentinel would land
        in range of the wider compiled one_hot). Raises NeedsRebuild past
        the ladder top (see _grow)."""
        nxt = bucket_for(self.pair_width + 1, _PAIR_WIDTH_BUCKETS)
        if nxt > _PAIR_WIDTH_BUCKETS[-1]:
            raise NeedsRebuild(
                f"pair width {nxt} beyond ladder top "
                f"{_PAIR_WIDTH_BUCKETS[-1]}")
        self.pair_width = nxt
        self._pair_dev = jnp.asarray(
            self._materialize_pairs(range(self.snapshot.padded_incidents)))
        self._apply_sharding()
        self._rearm_warm_growth()

    def _rearm_warm_growth(self) -> None:
        """After any shape change (rebuild, width or pair-width growth),
        re-warm the growth shapes in the background so the compile-free
        guarantee tracks the NEW current shapes, not the cold-start ones.
        One warm thread at a time: ``_warm_active`` is flipped only under
        ``_warm_lock`` — by this method before starting the thread and by
        the thread itself just before exiting — so a re-arm can never race
        a thread that already decided to exit (the pending flag is either
        drained by the running thread or a new thread starts; no TOCTOU
        window). NON-daemon: a daemon thread hard-killed inside an XLA
        compile at interpreter shutdown aborts the process (observed:
        'FATAL: exception not rethrown'); exit instead waits out at most
        one in-flight compile (stop_warm sets the cooperative flag)."""
        if not self.auto_warm_growth:
            return
        with self._warm_lock:
            if self._warm_stop:
                return
            if self._warm_active:
                self._warm_rearm_pending = True
                return
            self._warm_active = True
            self._warm_rearm_pending = False
            # daemon=False EXPLICITLY: Thread inherits the creating
            # thread's daemon flag, and serving threads are daemons — a
            # daemon warm thread hard-killed inside an XLA compile at
            # interpreter shutdown aborts the process
            self._warm_thread = threading.Thread(
                target=self._warm_growth_quiet, name="kaeg-warm-growth",
                daemon=False)
            self._warm_thread.start()

    def _rebuild(self) -> None:
        self.rebuilds += 1
        self._init_from_store()
        # re-arm: the guarantee "growth rebuilds never compile mid-serve"
        # must hold for the NEXT bucket too, not just the first growth
        self._rearm_warm_growth()

    # -- structural mutation API ------------------------------------------
    #
    # Callers mutate the store FIRST (it stays authoritative — rebuilds and
    # parity checks read it), then mirror the change here. Every method is
    # O(change); on bucket overflow it falls back to _rebuild().

    def add_entity(self, node_id: str) -> int:
        """New non-incident node: takes a free padded feature row.

        Returns -1 when row exhaustion forced a rebuild and the node is
        already gone from the store again (its add AND remove were both
        pending in one sync batch — the store-derived rebuild reflects the
        remove, so there is no row to report and none is needed)."""
        if node_id in self._id_to_idx:
            return self._id_to_idx[node_id]
        if not self._node_row_available(node_id):
            self._rebuild()
            return self._id_to_idx.get(node_id, -1)
        row = self._take_node_row(node_id)
        node = self._store_node(node_id)
        self._node_ids[row] = node_id
        self._id_to_idx[node_id] = row
        self.snapshot.node_mask[row] = 1.0
        if node is not None:
            self.snapshot.node_kind[row] = int(node.kind)
            feats = extract_node_features(node, now_s=self.now_s)
        else:
            feats = np.zeros(self.snapshot.features.shape[1], np.float32)
        self.snapshot.features[row] = feats
        self._pending_feat[row] = feats
        return row

    def remove_entity(self, node_id: str) -> bool:
        """Remove a node: drop its evidence occurrences everywhere, free
        its feature row, zero its features (stale gathers must fold 0)."""
        row = self._id_to_idx.pop(node_id, None)
        if row is None:
            return False
        for r in self._ev_rows_of_node.pop(row, set()):
            keep = [i for i, n in enumerate(self._row_nodes[r]) if n != row]
            self._row_nodes[r] = [self._row_nodes[r][i] for i in keep]
            self._row_pairs[r] = [self._row_pairs[r][i] for i in keep]
            self._recompact_pairs(r)  # the slot's pair key may now be stale
        self._del_pod_node(row)
        # if the removed entity was a SCHEDULED_ON target, pods lose their
        # node: their evidence slots revert to the no-pair sentinel (a full
        # rebuild would see no edge). Recompacting each affected row both
        # re-stamps those slots and evicts the dead node's pair key, so a
        # future allocation can never collide with it (ADVICE r2 high).
        # The reverse index makes this O(degree), not O(all pods).
        stranded = self._sched_pods.pop(row, set())
        if stranded:
            affected: set[int] = set()
            for p in stranded:
                del self._pod_node[p]
                affected |= self._ev_rows_of_node.get(p, set())
            for r in affected:
                self._recompact_pairs(r)
        self._node_ids[row] = None
        self._put_node_row(row)
        self.snapshot.node_mask[row] = 0.0
        self.snapshot.features[row] = 0.0
        self._pending_feat[row] = np.zeros(
            self.snapshot.features.shape[1], np.float32)
        return True

    def add_incident(self, incident_node_id: str,
                     evidence_node_ids: Iterable[str] = ()) -> int:
        """Incident arrival: a free incident row + its evidence slots.

        Returns -1 when bucket overflow forced a rebuild and the incident
        is already closed in the store (arrival and closure both pending
        in one sync batch: the rebuild tensorized the post-closure store,
        so the incident legitimately has no row)."""
        if incident_node_id in self._inc_row_of:
            r = self._inc_row_of[incident_node_id]
        else:
            if not self._inc_row_available(incident_node_id):
                self._rebuild()
                return self._inc_row_of.get(incident_node_id, -1)
            rb = self.rebuilds
            nrow = self.add_entity(incident_node_id)
            if self.rebuilds != rb:
                # node-row exhaustion rebuilt from the (already upserted)
                # store, which registered the incident — allocating a second
                # row here would leak the first one (or, if the incident was
                # closed later in the same sync batch, it has no row at all)
                return self._inc_row_of.get(incident_node_id, -1)
            r = self._take_inc_row(incident_node_id)
            self._inc_row_of[incident_node_id] = r
            self._row_inc[r] = incident_node_id
            self.snapshot.incident_nodes[r] = nrow
            self.snapshot.incident_mask[r] = 1.0
        for eid in evidence_node_ids:
            self.add_evidence(incident_node_id, eid)
        return r

    def close_incident(self, incident_node_id: str) -> bool:
        """Incident closure: clear the row's evidence and free it."""
        nid = self._canon_incident_id(incident_node_id)
        r = self._inc_row_of.pop(nid, None)
        if r is None:
            return False
        for dst in set(self._row_nodes[r]):
            s = self._ev_rows_of_node.get(dst)
            if s is not None:
                s.discard(r)
        self._row_nodes[r] = []
        self._row_pairs[r] = []
        self._pair_map[r] = {}
        self._row_inc[r] = None
        self._put_inc_row(r)
        self.snapshot.incident_mask[r] = 0.0
        self._dirty_rows.add(r)
        self.remove_entity(nid)
        return True

    def add_evidence(self, incident_node_id: str, entity_node_id: str) -> bool:
        """New AFFECTS/CORRELATES_WITH evidence edge."""
        r = self._inc_row_of.get(incident_node_id)
        dst = self._id_to_idx.get(entity_node_id)
        if r is None or dst is None:
            return False
        if dst in self._row_nodes[r]:
            return True  # MERGE semantics: duplicate edge is a no-op
        if len(self._row_nodes[r]) >= self.width:
            self._append_evidence_host(r, dst)
            # width first: the pair-growth path re-materializes at the
            # current width. A ladder-exhaustion rebuild supersedes row
            # state entirely (store-derived), so stop on escalation.
            if self._grow(self._grow_width):
                return True
            if self._pair_overflowed(r):
                self._grow(self._grow_pair_width)
            return True
        self._append_evidence_host(r, dst)
        if self._pair_overflowed(r):
            if self._grow(self._grow_pair_width):
                return True
        self._dirty_rows.add(r)
        return True

    def _pair_overflowed(self, r: int) -> bool:
        # check the MAX pid, not the map size: with holes (possible only
        # transiently mid-mutation) the max can reach pair_width — the
        # "no node" sentinel — while len(pm) still passes (ADVICE r2 high)
        return max(self._pair_map[r].values(), default=-1) + 1 > self.pair_width

    def remove_evidence(self, incident_node_id: str,
                        entity_node_id: str) -> bool:
        r = self._inc_row_of.get(incident_node_id)
        dst = self._id_to_idx.get(entity_node_id)
        if r is None or dst is None or dst not in self._row_nodes[r]:
            return False
        i = self._row_nodes[r].index(dst)
        del self._row_nodes[r][i]
        del self._row_pairs[r][i]
        if dst not in self._row_nodes[r]:
            s = self._ev_rows_of_node.get(dst)
            if s is not None:
                s.discard(r)
        self._recompact_pairs(r)  # prune the pair key if it lost its last ref
        return True

    def schedule_pod(self, pod_id: str, node_id: str) -> bool:
        """New or retargeted SCHEDULED_ON edge: every evidence slot holding
        this pod gets the pair id of the new node (allocating a row-local id
        if the node is new to that row)."""
        pod = self._id_to_idx.get(pod_id)
        node = self._id_to_idx.get(node_id)
        if pod is None or node is None:
            return False
        self._set_pod_node(pod, node)
        grew = False
        for r in self._ev_rows_of_node.get(pod, set()):
            # recompact rather than setdefault(len(pm)): the pod's OLD node
            # may have just lost its last reference in this row, and a
            # len-based id could collide with a live pid (ADVICE r2 high)
            self._recompact_pairs(r)
            if self._pair_overflowed(r):
                grew = True
        if grew:
            self._grow(self._grow_pair_width)
        return True

    # back-compat alias (round-1 API)
    def reschedule_pod(self, pod_id: str, new_node_id: str) -> bool:
        return self.schedule_pod(pod_id, new_node_id)

    def unschedule_pod(self, pod_id: str, node_id: str | None = None) -> bool:
        """SCHEDULED_ON edge deleted without a replacement: the pod's
        evidence slots revert to the no-pair sentinel. With ``node_id``,
        only applies if the pod is still mapped to THAT node — so an
        add-new-then-remove-old reschedule (edge+ nodeB, edge- nodeA)
        replays order-insensitively instead of stranding the pod."""
        pod = self._id_to_idx.get(pod_id)
        if pod is None or pod not in self._pod_node:
            return False
        if node_id is not None:
            node = self._id_to_idx.get(node_id)
            if node is not None and self._pod_node[pod] != node:
                return False   # already rescheduled elsewhere; stale delete
        self._del_pod_node(pod)
        for r in self._ev_rows_of_node.get(pod, set()):
            self._recompact_pairs(r)
        return True

    # -- store-journal mirroring (the serving path) ------------------------

    def sync(self) -> dict:
        """Drain the store's change journal into the resident state.

        This is what makes the scorer THE serving engine (VERDICT r2 item
        2): any writer — workflow graph ingest, API mutations, simulator
        churn — mutates the store as usual, and the scorer catches up in
        O(changes) instead of re-tensorizing the world per incident
        (the reference re-traverses Neo4j per incident,
        activities.py:26-164). Falls back to one full rebuild if the
        bounded journal evicted unseen records."""
        recs, seq, truncated = self.store.journal_since(self._synced_seq)
        self.syncs += 1
        if truncated:
            self._rebuild()
            return {"applied": 0, "rebuilt": True}
        res = self._apply_records(recs)
        if not res["rebuilt"]:
            self._synced_seq = max(seq, self._synced_seq)
        return res

    def _apply_records(self, recs: list) -> dict:
        """Apply one batch of store-journal records through the mutation
        API. Shared by sync() (records drained live from the store) and
        the shield's journal replay (records re-fed from the write-ahead
        log, rca/shield.py) — one code path is what makes replay
        bit-identical. The caller owns cursor bookkeeping; a mid-batch
        rebuild supersedes the batch (state is store-derived as of NOW)."""
        changed: set[str] = set()
        structural = 0
        incident_kind = int(EntityKind.INCIDENT)
        affects = (int(RelationKind.AFFECTS),
                   int(RelationKind.CORRELATES_WITH))
        sched = int(RelationKind.SCHEDULED_ON)
        rb0 = self.rebuilds
        for rec in recs:
            if self.rebuilds != rb0:
                # a mutation overflowed a bucket and rebuilt: the rebuild
                # tensorized the store as of NOW — which already includes
                # every remaining record in this batch (and advanced
                # _synced_seq past them). Stop replaying: continuing would
                # only re-queue redundant deltas, making the post-rebuild
                # dispatch land on cold large delta buckets instead of the
                # warmed minimal ones.
                return {"applied": len(recs), "rebuilt": True}
            op = rec[1]
            if op == "node~":
                changed.add(rec[2])
            elif op == "node+":
                if rec[3] == incident_kind:
                    self.add_incident(rec[2])
                else:
                    self.add_entity(rec[2])
                structural += 1
            elif op == "node-":
                if rec[3] == incident_kind:
                    self.close_incident(rec[2])
                else:
                    self.remove_entity(rec[2])
                structural += 1
            elif op == "edge+":
                src, dst, kind = rec[2], rec[3], rec[4]
                if kind == sched:
                    self.schedule_pod(src, dst)
                elif kind in affects:
                    if src in self._inc_row_of:
                        self.add_evidence(src, dst)
                    elif dst in self._inc_row_of:
                        self.add_evidence(dst, src)
                structural += 1
            elif op == "edge-":
                src, dst, kind = rec[2], rec[3], rec[4]
                if kind == sched:
                    self.unschedule_pod(src, dst)
                elif kind in affects:
                    if src in self._inc_row_of:
                        self.remove_evidence(src, dst)
                    elif dst in self._inc_row_of:
                        self.remove_evidence(dst, src)
                structural += 1
        if self.rebuilds != rb0:   # rebuild fired on the last record
            return {"applied": len(recs), "rebuilt": True}
        if changed:
            # applied last with CURRENT store state: latest feature wins
            # regardless of interleaving, and removed ids just skip
            self.update_nodes(changed)
        return {"applied": len(recs), "structural": structural,
                "feature": len(changed), "rebuilt": False}

    def update_nodes(self, node_ids: Iterable[str]) -> int:
        """Queue feature re-extraction for nodes whose properties changed."""
        n = 0
        for nid in node_ids:
            idx = self._id_to_idx.get(nid)
            node = self._store_node(nid)
            if idx is None or node is None:
                continue
            row = extract_node_features(node, now_s=self.now_s)
            self.snapshot.features[idx] = row  # keep host copy coherent
            self._pending_feat[idx] = row
            n += 1
        return n

    # -- scoring -----------------------------------------------------------

    def _pending_feature_delta(self) -> tuple[np.ndarray, np.ndarray]:
        """Drain queued feature updates into padded (idx, rows) arrays.
        The dict source guarantees unique indices — no duplicate-index
        scatter whose application order XLA leaves unspecified."""
        k = len(self._pending_feat)
        pk = bucket_for(max(k, 1), _DELTA_BUCKETS)
        pn = self.snapshot.padded_nodes
        idx = np.full(pk, pn, dtype=np.int32)      # out-of-range -> dropped
        rows = np.zeros((pk, self.snapshot.features.shape[1]), np.float32)
        if k:
            idx[:k] = list(self._pending_feat.keys())
            rows[:k] = np.stack(list(self._pending_feat.values()))
            self._pending_feat.clear()
        return idx, rows

    def _pending_feature_delta_sharded(self, shards: int
                                       ) -> tuple[np.ndarray, np.ndarray]:
        """Route queued feature updates to their owner shards with
        per-shard _DELTA_BUCKETS sub-buckets ([D, pk] local indices +
        [D, pk, DIM] rows): the compiled delta width follows the MAX
        per-shard count, so one hot shard never retraces the others, and
        each shard's deltas keep store-journal order (the pending dict
        preserves insertion order; the router must not reorder — the
        sort-contract test pins this)."""
        from ..parallel.sharded_streaming import route_node_delta
        nps = self.snapshot.padded_nodes // shards
        idx, per_shard, pk = route_node_delta(
            list(self._pending_feat.items()), nps, shards, _DELTA_BUCKETS)
        rows = np.zeros((shards, pk, self.snapshot.features.shape[1]),
                        np.float32)
        for g, ents in enumerate(per_shard):
            for j, (_row, feats) in enumerate(ents):
                rows[g, j] = feats
        self._pending_feat.clear()
        return idx, rows

    def _pending_feat_bound(self) -> int:
        """Pending feature-delta entries as the COMPILED tick will see
        them: the max per-shard count in graph-sharded mode (per-shard
        sub-buckets bound the coalescing ladder per shard), the total
        otherwise."""
        g = self._graph_size()
        if g > 1 and self._graph_sharded(self.snapshot.padded_nodes,
                                         self.snapshot.padded_incidents):
            nps = self.snapshot.padded_nodes // g
            counts = [0] * g
            for row in self._pending_feat:
                counts[row // nps] += 1
            return max(counts, default=0)
        return len(self._pending_feat)

    def _pending_row_delta(self) -> tuple[np.ndarray, ...]:
        """Drain dirty incident rows into padded scatter arrays."""
        rows = sorted(self._dirty_rows)
        self._dirty_rows.clear()
        k = len(rows)
        pk = bucket_for(max(k, 1), _ROW_BUCKETS)
        pi = self.snapshot.padded_incidents
        r_idx = np.full(pk, pi, dtype=np.int32)    # out-of-range -> dropped
        r_ev = np.zeros((pk, self.width), np.int32)
        r_cnt = np.zeros(pk, np.int32)
        r_pair = np.full((pk, self.width), self.pair_width, np.int32)
        if k:
            ev_idx, ev_cnt, ev_pair = self._materialize_rows(rows)
            r_idx[:k] = rows
            r_ev[:k], r_cnt[:k], r_pair[:k] = ev_idx, ev_cnt, ev_pair
        return r_idx, r_ev, r_cnt, r_pair

    def _staged_extra_ints(self) -> "np.ndarray | None":
        """Extra int32 payload a subclass wants folded into the staged
        slab (graft-fuse: the GNN scorer rides its packed aux/edge delta
        on the base slab's transfer — see GnnStreamingScorer). The base
        scorer stages nothing extra."""
        return None

    def _staged_delta_columnar(self):
        """graft-intake: drain pending deltas into ONE device-ready int32
        slab — layout ``[f_idx | r_idx | r_cnt | r_ev | r_pair |
        f_rows.bitcast(int32) | extra_ints]``, the exact ``_pack_ints``
        prefix followed by the feature rows (and any subclass extra
        payload — graft-fuse folds the GNN delta here), so the jitted
        ``_delta_pack`` splits it on device and the tick pays a single
        host→device transfer. The feature segment fills by
        FeatureStage.drain_into (a memcpy); the (small) row-delta arrays
        copy into their slab segments. Returns ``(slab, f_idx_view,
        f_rows_view, li, pk, rk, gi)``; the views alias the slab, so the
        fault/screen seams edit the staged bytes the device will
        actually read."""
        stage = self._pending_feat
        pn = self.snapshot.padded_nodes
        dim = self.snapshot.features.shape[1]
        width = self.width
        k = len(stage)
        pk = bucket_for(max(k, 1), _DELTA_BUCKETS)
        r_idx, r_ev, r_cnt, r_pair = self._pending_row_delta()
        rk = len(r_idx)
        li = pk + 2 * rk + 2 * rk * width
        extra = self._staged_extra_ints()
        gi = 0 if extra is None else int(extra.size)
        slab = self._stage_pool.acquire(li + pk * dim + gi)
        f_idx = slab[:pk]
        slab[pk:pk + rk] = r_idx
        slab[pk + rk:pk + 2 * rk] = r_cnt
        off = pk + 2 * rk
        slab[off:off + rk * width] = r_ev.ravel()
        slab[off + rk * width:li] = r_pair.ravel()
        f_rows = slab[li:li + pk * dim].view(np.float32).reshape(pk, dim)
        stage.drain_into(f_idx, f_rows, pn)
        if gi:
            slab[li + pk * dim:] = extra
        obs_metrics.INGEST_BATCH_FILL.set(k / pk, site="delta")
        return slab, f_idx, f_rows, li, pk, rk, gi

    def warm(self, delta_sizes: tuple[int, ...] = (64, 256),
             row_sizes: tuple[int, ...] = (4, 16),
             include_next_width: bool = False) -> None:
        """Pre-compile the fused tick for the given delta buckets plus the
        NEXT pair-width bucket (a reschedule can bump it mid-stream), so hot
        ticks never pay a compile. ``include_next_width=True`` additionally
        warms the next slot-WIDTH bucket (stand-in zero tables at that
        width), so an evidence-append overflow doesn't compile in the hot
        loop either — at roughly double the warm-up compiles."""
        if not delta_sizes:
            return
        # capture a CONSISTENT view under serve_lock (a concurrent rebuild
        # swapping shapes mid-capture hands jit mismatched operand shapes);
        # the expensive compiles then run outside the lock
        with self.serve_lock:
            pn = self.snapshot.padded_nodes
            pi = self.snapshot.padded_incidents
            dim = self.snapshot.features.shape[1]
            cur_w = self.pair_width
            cur_width = self.width
            chain0 = self._chain0
            sharded = self._sharded(pi)
            shardings = self._shardings(pn, pi) if sharded else None
            gshards = (self._graph_size()
                       if self._graph_sharded(pn, pi) else 1)
            columnar = isinstance(self._pending_feat, FeatureStage)
        next_w = next((w for w in _PAIR_WIDTH_BUCKETS if w > cur_w), cur_w)
        widths = [cur_width]
        if include_next_width:
            widths.append(bucket_for(cur_width + 1, _WIDTH_BUCKETS))

        def standins(width: int, pw: int):
            # the tick DONATES (features, ev_idx, ev_cnt, ev_pair): handing
            # it the live resident handles would invalidate the serving
            # state, so every warm call consumes a FRESH zero stand-in set
            # (a donated buffer is dead after one execution) — placed like
            # the live state, since executables key on input shardings
            feats = jnp.zeros((pn, dim), jnp.float32)
            tables = (jnp.zeros((pi, width), jnp.int32),
                      jnp.zeros((pi,), jnp.int32),
                      jnp.full((pi, width), pw, jnp.int32))
            if sharded:
                rep, row1, row2 = shardings
                feats = jax.device_put(feats, rep)
                tables = (jax.device_put(tables[0], row2),
                          jax.device_put(tables[1], row1),
                          jax.device_put(tables[2], row2))
            return feats, tables

        for width in widths:
            for pk in delta_sizes:
                if gshards > 1:
                    # sharded tick: per-shard LOCAL indices, sentinel =
                    # nodes-per-shard (all-dropped), [G, pk, DIM] rows
                    f_idx = np.full((gshards, pk), pn // gshards, np.int32)
                    f_rows = np.zeros((gshards, pk, dim), np.float32)
                else:
                    f_idx = np.full(pk, pn, dtype=np.int32)  # all-dropped
                    f_rows = np.zeros((pk, dim), np.float32)
                for rk in row_sizes or (_ROW_BUCKETS[0],):
                    r_idx = np.full(rk, pi, dtype=np.int32)
                    r_ev = np.zeros((rk, width), np.int32)
                    r_cnt = np.zeros(rk, np.int32)
                    if gshards == 1 and columnar:
                        # graft-intake: the columnar dispatch runs
                        # _delta_pack before the tick — pre-compile its
                        # (li, pk, dim) variant too, or the first real
                        # tick at this combo pays the compile mid-serve
                        # gi=0 passed EXPLICITLY: pjit keys its cache on
                        # the static kwargs as passed, so a defaulted gi
                        # here would warm an entry the live dispatch
                        # (which always passes gi=slab_gi) never hits
                        li = pk + 2 * rk + 2 * rk * width
                        _delta_pack(jnp.zeros(li + pk * dim, jnp.int32),
                                    li=li, pk=pk, dim=dim, gi=0)
                    for pw in {cur_w, next_w}:
                        # graft-audit: allow[lock-guard] cooperative-cancel fast path: a stale read only delays the stop by one warm compile step
                        if self._warm_stop:
                            return
                        r_pair = np.full((rk, width), pw, np.int32)
                        ints = (_pack_ints_sharded(f_idx, r_idx, r_cnt,
                                                   r_ev, r_pair)
                                if gshards > 1 else
                                _pack_ints(f_idx, r_idx, r_cnt, r_ev,
                                           r_pair))
                        feats, tables = standins(width, pw)
                        self._tick_fn(pn, pi, width, pw, pk=pk, rk=rk)(
                            feats, jnp.asarray(ints),
                            jnp.asarray(f_rows), *tables, chain0)
        # READ-ONLY with respect to serving: results discarded and the
        # live resident handles are never passed to the donating tick
        # (stand-ins compile the exact executables the serving shapes
        # hit), which is what keeps warm() safe to run from a background
        # thread concurrently with serving dispatches

    def _rebuild_widths(self) -> tuple[int, int]:
        """(width, pair_width) a rebuild would derive from CURRENT host
        state — mirrors _init_from_store exactly (4/3 slack on the slot
        width, none on pairs), so warm_growth compiles the shapes the
        rebuild will actually land on, not guesses."""
        max_w = max(max((len(v) for v in self._row_nodes), default=1), 1)
        width = bucket_for(max(int(np.ceil(max_w * 4 / 3)), 1),
                           _WIDTH_BUCKETS)
        pw = bucket_for(
            max(max((len(m) for m in self._pair_map), default=1), 1),
            _PAIR_WIDTH_BUCKETS)
        return width, pw

    def _growth_shape_combos(self) -> list[tuple[int, int, int, int, int]]:
        """Snapshot, under serve_lock, the (pn, pi, width, pair_width, dim)
        combos a rebuild could land on: what a rebuild of the CURRENT
        store would derive (it can SHRINK after churn-down, or jump
        multiple buckets after a burst — both store-derived here, not
        guessed) plus one bucket of growth headroom, at the widths
        _rebuild_widths computes and the next pair bucket
        (_grow_pair_width can bump the current value between warm and
        rebuild). Taking serve_lock prevents torn reads of half-rebuilt
        host state; the expensive compiles happen outside the lock."""
        with self.serve_lock:
            pn, pi = self.snapshot.padded_nodes, self.snapshot.padded_incidents
            dim = self.snapshot.features.shape[1]
            # mirror build_snapshot(slack=1/3)'s bucket choice from store
            # counts — what _init_from_store would land on right now
            pn_now = bucket_for(
                max(int(np.ceil(len(self.store._nodes) * 4 / 3)), 1),
                self.settings.node_bucket_sizes)
            pi_now = bucket_for(
                max(int(np.ceil(len(self._inc_row_of) * 4 / 3)), 1),
                self.settings.incident_bucket_sizes)
            next_pn = bucket_for(pn + 1, self.settings.node_bucket_sizes)
            next_pi = bucket_for(pi + 1, self.settings.incident_bucket_sizes)
            rw, rpw = self._rebuild_widths()
            next_pw = next((w for w in _PAIR_WIDTH_BUCKETS
                            if w > self.pair_width), self.pair_width)
            # the next slot-WIDTH bucket too: _grow_width (evidence-append
            # overflow) is the remaining shape-growth axis, and it re-arms
            # this warm but the FIRST overflow must not compile mid-serve
            widths = {self.width, rw,
                      bucket_for(self.width + 1, _WIDTH_BUCKETS)}
            pws = {self.pair_width, rpw, next_pw}
            # (pn, pi) itself is included: a _grow_width overflow keeps the
            # CURRENT node/incident shape, which after store-count drift may
            # match none of the rebuild-derived or next buckets (ADVICE r4)
            shapes = {(pn, pi), (pn_now, pi_now), (next_pn, pi),
                      (pn, next_pi), (next_pn, next_pi)}
        return [(cpn, cpi, w, pw, dim)
                for (cpn, cpi) in shapes for w in widths for pw in pws]

    def warm_growth(self) -> None:
        """Pre-compile the fused tick at every shape a rebuild could land
        on (see _growth_shape_combos) so a bucket-overflow rebuild
        mid-serve pays tensorize + upload but NOT an XLA compile (~2 s
        hiccup measured at the serving bench when uncached). The delta
        buckets warmed per shape come from ``_growth_warm_buckets`` —
        the smallest ones for the base scorer (sync() stops replaying
        once a rebuild fires, so the post-rebuild dispatch carries ~no
        deltas); the multi-tenant pack widens the ladder (see the seam).
        Stand-in zero states at the target shapes are compiled and
        discarded; the jit cache keys on shapes, so the later real rebuild
        hits the cache. Runs on background threads (worker cold start +
        auto re-arm on every shape change when ``auto_warm_growth`` is
        set); stop_warm() bounds shutdown to the one in-flight compile."""
        pks, rks = self._growth_warm_buckets()
        columnar = isinstance(self._pending_feat, FeatureStage)
        for cpn, cpi, width, pw, dim in self._growth_shape_combos():
            sharded = self._sharded(cpi)
            shardings = self._shardings(cpn, cpi) if sharded else None
            gshards = (self._graph_size()
                       if self._graph_sharded(cpn, cpi) else 1)

            def standins():
                # FRESH per tick call: the tick donates its state inputs,
                # so a reused stand-in would be a dead buffer — placed
                # like the real rebuilt state will be (executables key on
                # input shardings)
                feats = jnp.zeros((cpn, dim), jnp.float32)
                tables = (jnp.zeros((cpi, width), jnp.int32),
                          jnp.zeros((cpi,), jnp.int32),
                          jnp.full((cpi, width), pw, jnp.int32))
                chain = jnp.zeros((cpi,), jnp.float32)
                if sharded:
                    rep, row1, row2 = shardings
                    feats = jax.device_put(feats, rep)
                    tables = (jax.device_put(tables[0], row2),
                              jax.device_put(tables[1], row1),
                              jax.device_put(tables[2], row2))
                    chain = jax.device_put(chain, row1)
                return feats, tables, chain

            for pk in pks:
                for rk in rks:
                    # graft-audit: allow[lock-guard] cooperative-cancel fast path: a stale read only delays the stop by one warm compile step
                    if self._warm_stop:
                        return
                    feats, tables, chain = standins()
                    if gshards > 1:
                        ints = _pack_ints_sharded(
                            np.full((gshards, pk), cpn // gshards,
                                    np.int32),
                            np.full(rk, cpi, np.int32),
                            np.zeros(rk, np.int32),
                            np.zeros((rk, width), np.int32),
                            np.full((rk, width), pw, np.int32))
                        f_rows = np.zeros((gshards, pk, dim), np.float32)
                    else:
                        ints = _pack_ints(
                            np.full(pk, cpn, np.int32),  # all-dropped
                            np.full(rk, cpi, np.int32),
                            np.zeros(rk, np.int32),
                            np.zeros((rk, width), np.int32),
                            np.full((rk, width), pw, np.int32))
                        f_rows = np.zeros((pk, dim), np.float32)
                        if columnar:
                            # pre-compile the matching _delta_pack split
                            # (the columnar dispatch runs it pre-tick)
                            li = pk + 2 * rk + 2 * rk * width
                            _delta_pack(
                                jnp.zeros(li + pk * dim, jnp.int32),
                                li=li, pk=pk, dim=dim, gi=0)
                    self._tick_fn(cpn, cpi, width, pw, pk=pk, rk=rk)(
                        feats, jnp.asarray(ints),
                        jnp.asarray(f_rows), *tables, chain)

    def _growth_warm_buckets(self) -> "tuple[tuple[int, ...], tuple[int, ...]]":
        """(pk ladder, rk ladder) warm_growth compiles per target shape.
        The base scorer's post-rebuild dispatch always lands on the
        smallest delta buckets (sync() stops replaying once a rebuild
        fires, so the next tick carries ~no deltas). The multi-tenant
        pack overrides this: a mid-batch incremental repack leaves the
        KEPT tenants' un-drained journal records for the next sync, so
        its first post-repack ticks legitimately carry multi-tenant
        delta batches on larger buckets (rca/surge.py)."""
        return (_DELTA_BUCKETS[:1], _ROW_BUCKETS[:1])

    def warm_serving(self) -> None:
        """Cold-start warm for the serving path, run off-thread by the
        worker: steady-state delta buckets incl. the next slot-width
        bucket (warm(), read-only) plus the growth shapes via the re-arm
        machinery."""
        try:
            self.warm(delta_sizes=(64, 256), row_sizes=(4, 16),
                      include_next_width=True)
        except Exception as exc:  # graft-audit: allow[broad-except] best-effort warm: a failed pre-compile only costs a later compile
            log.warning("warm_serving_failed", error=str(exc))
        self._rearm_warm_growth()

    def _warm_growth_quiet(self) -> None:
        while True:
            try:
                self.warm_growth()
            except Exception as exc:  # graft-audit: allow[broad-except] a failed pre-compile only means the
                log.warning(          # next rebuild pays the compile itself
                    "warm_growth_failed", error=str(exc))
            with self._warm_lock:
                if self._warm_stop or not self._warm_rearm_pending:
                    self._warm_active = False
                    return
                self._warm_rearm_pending = False   # shapes changed mid-warm

    def stop_warm(self, join: bool = True) -> None:
        """Cooperative shutdown for the background warms: bounds process
        exit to at most the one in-flight compile instead of the full
        shape-combo product. Reversible — resume_warm() re-enables."""
        with self._warm_lock:
            self._warm_stop = True
            self._warm_rearm_pending = False
            t = self._warm_thread
        if join and t is not None and t.is_alive():
            t.join()

    def resume_warm(self) -> None:
        """Re-enable background warming after stop_warm (a worker drain
        sets the stop flag; a later start() must not silently serve with
        the compile-free guarantee disabled)."""
        with self._warm_lock:
            self._warm_stop = False

    def dispatch(self) -> tuple:
        """Flush pending deltas and enqueue one scoring pass; returns the
        device result handles without a host fetch (the dev tunnel charges
        ~75 ms per synchronous fetch — see tpu_backend.dispatch).

        graft-scope: the tick's TickSpan is opened here and stamped at
        each host boundary — ``staging`` when the packed deltas are
        ready, ``dispatch`` when the jit enqueue returns. The span parks
        in ``_last_tick_span`` for the caller (tick_async queues it with
        the in-flight handles; rescore finalizes it at the fetch)."""
        if self._last_tick_span is not None:
            # the previous tick aborted between dispatch and its caller
            # boundary (an injected fault, a device error): record it
            # rather than silently overwrite — faulted ticks are exactly
            # what the flight recorder exists to explain
            self._last_tick_span.flag("abandoned")
            self.scope.finalize(self._last_tick_span)
            self._last_tick_span = None
        span = self.scope.begin(self)
        self._last_tick_span = span
        if span is not None:
            span.pending = len(self._pending_feat) + len(self._dirty_rows)
            span.coalesced = self._scope_coalesced_since
            span.params_gen = self.params_generation
            self._scope_coalesced_since = 0
        sharded = self._graph_sharded(self.snapshot.padded_nodes,
                                      self.snapshot.padded_incidents)
        # graft-intake: the columnar staging path drains the FeatureStage
        # with a memcpy into ONE device-ready int32 slab (packed ints +
        # bitcast feature rows); the dict path below is the bit-parity
        # oracle. Sharded serving keeps the per-shard routed layout (its
        # ints are [G, L]; routing stays the per-shard delta story).
        columnar = (not sharded
                    and isinstance(self._pending_feat, FeatureStage))
        slab = None
        slab_gi = 0
        if columnar:
            slab, f_idx, f_rows, slab_li, pk, rk, slab_gi = \
                self._staged_delta_columnar()
        elif sharded:
            f_idx, f_rows = self._pending_feature_delta_sharded(
                self._graph_size())
            r_idx, r_ev, r_cnt, r_pair = self._pending_row_delta()
        else:
            f_idx, f_rows = self._pending_feature_delta()
            r_idx, r_ev, r_cnt, r_pair = self._pending_row_delta()
        if span is not None:
            # sub-mark: host delta drain + row materialization + (on the
            # columnar path) the packed-slab assembly — the "pack" half
            # of what used to be one opaque staging segment
            span.mark("pack")
        # graft-shield hooks: value poisoning lands on the STAGED rows
        # (the host copy in self.snapshot stays clean — store-truth), and
        # the dispatch fault fires after the pending deltas were drained,
        # so a bare retry cannot restage them: journal replay must
        poisoned = self._fault_value("delta_values", f_rows)
        if poisoned is not f_rows:
            if columnar:
                # keep the slab authoritative: the poison must ride the
                # PACKED buffer the device actually reads, or the chaos
                # suite would prove nothing about the columnar path
                f_rows[...] = poisoned
            else:
                f_rows = poisoned
        s_idx, s_rows = self._screen_delta(f_idx, f_rows, span)
        if columnar and (s_idx is not f_idx or s_rows is not f_rows):
            # the multi-tenant screen returns edited copies (quarantined
            # rows sentineled) — fold them back into the staged slab
            f_idx[...], f_rows[...] = s_idx, s_rows
        else:
            f_idx, f_rows = s_idx, s_rows
        self._fault_point("dispatch")
        if not columnar:
            if sharded:
                ints = _pack_ints_sharded(f_idx, r_idx, r_cnt, r_ev, r_pair)
            else:
                ints = _pack_ints(f_idx, r_idx, r_cnt, r_ev, r_pair)
            pk, rk = f_idx.shape[-1], len(r_idx)
        # the packed buffers exist now on either path: the staging fault
        # class extends to them (a lost pack is dispatch-like — deltas
        # are drained, only journal replay can restage)
        self._fault_point("pack")
        tick = self._tick_fn(self.snapshot.padded_nodes,
                             self.snapshot.padded_incidents,
                             self.width, self.pair_width,
                             pk=pk, rk=rk)
        if columnar:
            # graft-audit: allow[retrace-unbounded-static] dim is the architecture-fixed feature width (graph.schema.DIM, invariant across rebuilds), not a churn-driven count — reading it off the resident table keeps the pack aligned with whatever snapshot is live
            packed = _delta_pack(
                jnp.asarray(slab), li=slab_li, pk=pk,
                dim=self.snapshot.features.shape[1], gi=slab_gi)
            ints_dev, rows_dev = packed[0], packed[1]
            # graft-fuse: the GNN delta rode the same slab — park its
            # on-device slice for the subclass's tick (one transfer)
            self._staged_gnn_dev = packed[2] if slab_gi else None
        else:
            ints_dev = jnp.asarray(ints)
            rows_dev = jnp.asarray(f_rows)
            self._staged_gnn_dev = None
        args = (self._features_dev, ints_dev, rows_dev,
                self._ev_idx_dev, self._ev_cnt_dev, self._pair_dev,
                self._chain0)
        if span is not None:
            span.mark("staging")
            # roofline drift: price THIS tick's jaxpr with the graft-cost
            # model, cached per compiled shape key (make_jaxpr is
            # abstract — it neither executes nor consumes the donated
            # buffers, and re-traces exactly when XLA itself recompiles)
            self._scope_key = (self.snapshot.padded_nodes,
                               self.snapshot.padded_incidents,
                               self.width, self.pair_width,
                               pk, rk, sharded)
            self._scope_entry = self._scope_entrypoint(sharded)
            obs_scope.ROOFLINE.model(self._scope_entry, self._scope_key,
                                     tick, args, pack=self._scope_pack)
        out = tick(*args)
        (self._features_dev, self._ev_idx_dev, self._ev_cnt_dev,
         self._pair_dev) = out[:4]
        # device error / preemption mid-pipeline: the donated inputs are
        # already dead and the outputs may be poisoned — the shield's
        # recovery tiers are the only way back to the pre-fault state
        self._fault_point("execute")
        # graft-heal: per-shard device faults on the graph-sharded state
        # (a single mesh position's block dies, localized — the shield's
        # shard-loss classifier distinguishes this from whole-device loss)
        self._fault_point("shard_loss")
        self.dispatches += 1
        # graft-surge: every device pass scores EVERY live incident on
        # the resident state — the histogram makes cross-tenant batching
        # visible (N incidents / pass, labeled by how many tenants packed)
        batch = len(self._inc_row_of)
        obs_metrics.SERVE_BATCH_INCIDENTS.observe(
            float(batch), tenants=str(self._tenant_count()))
        if span is not None:
            span.batch_incidents = batch
            span.tenants = self._tenant_count()
            span.mark("dispatch")
        return out[4:]

    def _screen_delta(self, f_idx: np.ndarray, f_rows: np.ndarray,
                      span) -> tuple[np.ndarray, np.ndarray]:
        """Finite guard over the staged feature rows, applied after the
        pending deltas were drained and before they scatter into the
        donated state. The base scorer raises :class:`NonFiniteDelta`
        (the shield quarantines the batch and replays); the multi-tenant
        pack overrides this to quarantine only the POISONED tenants'
        rows so the other tenants' tick proceeds (rca/surge.py)."""
        if self.finite_delta_guard and not np.isfinite(f_rows).all():
            # O(delta) host check, not O(N): quarantine-grade poison is
            # caught BEFORE it scatters into the donated state
            if span is not None:
                span.flag("nonfinite_delta")
                self.scope.finalize(span)
                self._last_tick_span = None
            raise NonFiniteDelta(
                f"{int((~np.isfinite(f_rows)).any(axis=-1).sum())} "
                "non-finite staged feature rows")
        return f_idx, f_rows

    def _scope_entrypoint(self, sharded: bool) -> str:
        return ("streaming.rules_tick.sharded" if sharded
                else "streaming.rules_tick")

    # -- graft-shield seams (fault injection + snapshot/restore) -----------

    def _fault_point(self, stage: str) -> None:
        inj = self.fault_injector
        if inj is not None:
            inj.at(stage, self)

    def _fault_value(self, stage: str, value: np.ndarray) -> np.ndarray:
        inj = self.fault_injector
        if inj is not None:
            return inj.poison(stage, value)
        return value

    # Host-authoritative attributes a state snapshot must carry: together
    # with the resident device arrays they reproduce the scorer exactly
    # (free lists included — replayed mutations must allocate the same
    # rows for bit-identical recovery). Kept as an explicit tuple so the
    # shield can pickle/restore without knowing scorer internals.
    _HOST_STATE_ATTRS: tuple[str, ...] = (
        "snapshot", "width", "pair_width", "_synced_seq",
        "_node_ids", "_id_to_idx", "_free_node_rows",
        "_inc_row_of", "_row_inc", "_free_inc_rows",
        "_pod_node", "_sched_pods",
        "_row_nodes", "_row_pairs", "_pair_map", "_ev_rows_of_node",
        "_pending_feat", "_dirty_rows",
    )

    def capture_host_state(self) -> dict:
        """References to the host-side serving state (the shield pickles
        them immediately, under serve_lock, so later mutation cannot leak
        into the snapshot).

        The GraphSnapshot is captured SLIM: ``features`` is dropped (the
        host mirror is bit-identical to the resident device buffer, which
        the snapshot already packs — restore re-stitches it from there)
        and the edge arrays are dropped (read only by _init_from_store;
        a post-restore rebuild re-derives them from the store). At the
        50k-pod config this halves snapshot bytes and capture time."""
        import dataclasses
        d = {k: getattr(self, k) for k in self._HOST_STATE_ATTRS}
        d["snapshot"] = dataclasses.replace(
            self.snapshot,
            features=np.zeros((0, self.snapshot.features.shape[1]),
                              np.float32),
            edge_src=np.zeros(0, np.int32), edge_dst=np.zeros(0, np.int32),
            edge_rel=np.zeros(0, np.int32),
            edge_mask=np.zeros(0, np.float32))
        return d

    def restore_host_state(self, state: dict) -> None:
        """Adopt a deserialized host-state dict (fresh objects from
        pickle — never shared with a live scorer). The feature mirror is
        re-stitched from the restored device buffer by _adopt_resident."""
        for k in self._HOST_STATE_ATTRS:
            setattr(self, k, state[k])
        self._inflight.clear()
        self._inflight_meta.clear()

    def _resident_arrays(self) -> list:
        """The device-resident buffers a snapshot packs, in a fixed order
        matching _adopt_resident. Subclasses extend with their mirrors."""
        return [self._features_dev, self._ev_idx_dev, self._ev_cnt_dev,
                self._pair_dev]

    def _adopt_resident(self, parts: tuple) -> None:
        """Re-install unpacked device buffers as the resident state, and
        re-stitch the host feature mirror from the device copy (the two
        are bit-identical by construction, so the snapshot carries the
        features once — see capture_host_state)."""
        (self._features_dev, self._ev_idx_dev, self._ev_cnt_dev,
         self._pair_dev) = (jnp.asarray(p) for p in parts[:4])
        if self.snapshot.features.size == 0:
            import dataclasses
            self.snapshot = dataclasses.replace(
                self.snapshot,
                features=np.array(jax.device_get(self._features_dev)))
        pi = self.snapshot.padded_incidents
        self._chain0 = jnp.zeros((pi,), jnp.float32)
        self._apply_sharding()

    # -- graft-heal seams (live resharding) --------------------------------

    def adopt_mesh(self, mesh) -> None:
        """graft-heal: re-point the resident serving state at a DIFFERENT
        (1 x D') serving mesh — live resharding after a classified shard
        loss (D' < D onto the survivors) or re-expansion when the device
        returns (D' -> D). Caller holds ``serve_lock`` (the shield's
        ``mesh_heal``); the flip happens at a queue generation boundary:
        every in-flight tick is superseded (it completes on the OLD mesh,
        its result is dropped unfetched — the graft-evolve hot-swap
        discipline) and the device state is RE-DERIVED from the
        host-truth mirrors (``snapshot.features`` is bit-identical to the
        resident buffer by the mirror contract; the evidence tables
        re-materialize from the authoritative host lists), so a corrupted
        dead-shard block never survives into the healed placement. Host
        bookkeeping — row maps, free lists, pair maps — is untouched:
        the healed scorer is the same scorer on a different mesh, which
        is what makes post-heal rules verdicts bit-identical to a fresh
        D' build. Pending host deltas are already reflected in the host
        mirrors (mutations write host-first), so they are dropped rather
        than redundantly re-scattered."""
        self._supersede_inflight()
        self.mesh = mesh
        pi = self.snapshot.padded_incidents
        self._features_dev = jnp.asarray(
            np.ascontiguousarray(self.snapshot.features))
        ev_idx, ev_cnt, ev_pair = self._materialize_rows(range(pi))
        self._ev_idx_dev = jnp.asarray(ev_idx)
        self._ev_cnt_dev = jnp.asarray(ev_cnt)
        self._pair_dev = jnp.asarray(ev_pair)
        self._chain0 = jnp.zeros((pi,), jnp.float32)
        self._pending_feat.clear()
        self._dirty_rows.clear()
        self._apply_sharding()
        self._rearm_warm_growth()

    def warm_mesh(self, mesh, delta_sizes: tuple[int, ...] = (64,),
                  row_sizes: tuple[int, ...] = (4,)) -> None:
        """graft-heal: pre-compile the serving tick at the CURRENT shapes
        on a DIFFERENT (survivor/home) mesh, so the first post-heal tick
        pays upload, not an XLA compile — the warm() discipline applied
        to the heal target. Read-only with respect to serving: stand-in
        zero states only, placed on the TARGET mesh (executables key on
        input shardings)."""
        from jax.sharding import NamedSharding, PartitionSpec as P
        with self.serve_lock:
            pn = self.snapshot.padded_nodes
            pi = self.snapshot.padded_incidents
            dim = self.snapshot.features.shape[1]
            width, pw = self.width, self.pair_width
            columnar = isinstance(self._pending_feat, FeatureStage)
        g = (mesh.shape["graph"]
             if mesh is not None and "graph" in mesh.axis_names else 1)
        if g > 1 and pn % g:
            return
        if g > 1:
            # the heal forces a fresh snapshot at the next generation
            # boundary: warm the attestation fold and the snapshot pack
            # at the TARGET placement too, or the first post-heal
            # boundary pays their compiles inside the recovery window
            from jax.sharding import NamedSharding as _NS
            from .heal import attest_fold
            from .shield import _snapshot_pack
            gsh = _NS(mesh, P("graph"))
            r1 = _NS(mesh, P("dp"))
            r2 = _NS(mesh, P("dp", None))
            feats = jax.device_put(jnp.zeros((pn, dim), jnp.float32), gsh)
            tables = (jax.device_put(
                          jnp.zeros((pi, width), jnp.int32), r2),
                      jax.device_put(jnp.zeros((pi,), jnp.int32), r1),
                      jax.device_put(
                          jnp.full((pi, width), pw, jnp.int32), r2))
            attest_fold(feats, shards=g)
            _snapshot_pack(feats, *tables)
        for pk in delta_sizes:
            for rk in row_sizes or (_ROW_BUCKETS[0],):
                # graft-audit: allow[lock-guard] cooperative-cancel fast path: a stale read only delays the stop by one warm compile step
                if self._warm_stop:
                    return
                if g > 1:
                    from ..parallel.sharded_streaming import (
                        sharded_rules_tick)
                    tick = sharded_rules_tick(mesh, pn // g, pi, pw,
                                              pk, rk, width)
                    gsh = NamedSharding(mesh, P("graph"))
                    r1 = NamedSharding(mesh, P("dp"))
                    r2 = NamedSharding(mesh, P("dp", None))
                    ints = _pack_ints_sharded(
                        np.full((g, pk), pn // g, np.int32),
                        np.full(rk, pi, np.int32), np.zeros(rk, np.int32),
                        np.zeros((rk, width), np.int32),
                        np.full((rk, width), pw, np.int32))
                    tick(jax.device_put(
                            jnp.zeros((pn, dim), jnp.float32), gsh),
                         jnp.asarray(ints),
                         jnp.asarray(np.zeros((g, pk, dim), np.float32)),
                         jax.device_put(
                            jnp.zeros((pi, width), jnp.int32), r2),
                         jax.device_put(jnp.zeros((pi,), jnp.int32), r1),
                         jax.device_put(
                            jnp.full((pi, width), pw, jnp.int32), r2),
                         jax.device_put(
                            jnp.zeros((pi,), jnp.float32), r1))
                else:
                    if columnar:
                        # the unsharded columnar dispatch runs _delta_pack
                        # before the tick, and warm() skips that combo
                        # while the scorer is still graph-sharded — warm
                        # it here or the first post-heal sync pays its
                        # compile inside the recovery window
                        # gi=0 explicit for the same pjit static-kwargs
                        # cache-keying reason as warm()
                        li = pk + 2 * rk + 2 * rk * width
                        _delta_pack(jnp.zeros(li + pk * dim, jnp.int32),
                                    li=li, pk=pk, dim=dim, gi=0)
                    ints = _pack_ints(
                        np.full(pk, pn, np.int32),
                        np.full(rk, pi, np.int32), np.zeros(rk, np.int32),
                        np.zeros((rk, width), np.int32),
                        np.full((rk, width), pw, np.int32))
                    _tick(jnp.zeros((pn, dim), jnp.float32),
                          jnp.asarray(ints),
                          jnp.asarray(np.zeros((pk, dim), np.float32)),
                          jnp.zeros((pi, width), jnp.int32),
                          jnp.zeros((pi,), jnp.int32),
                          jnp.full((pi, width), pw, jnp.int32),
                          jnp.zeros((pi,), jnp.float32),
                          padded_incidents=pi, pair_width=pw,
                          pk=pk, rk=rk, width=width)

    def _attest_arrays(self) -> list[tuple[str, np.ndarray]]:
        """graft-heal: (device attr, host-truth mirror) pairs the
        per-shard attestation fold covers — node-addressed resident
        arrays whose host copies are bit-identical by the mirror
        contract. Subclasses extend with their aux mirrors."""
        return [("_features_dev", self.snapshot.features)]

    # -- pipelined executor (graft-pipeline) -------------------------------
    #
    # dispatch() is async already (jax enqueues and returns handles); what
    # serialized the old loop was the blocking jax.device_get after EVERY
    # tick. The executor splits the two: tick_async() submits ticks into a
    # bounded in-flight queue (depth = settings.serve_pipeline_depth) so
    # the host packs tick t+1 while the device runs tick t, and the fetch
    # happens once at the caller boundary (rescore()/serve()), dropping
    # superseded results without a readback. Backpressure is adaptive
    # coalescing: a full queue leaves the deltas pending, where they merge
    # into one larger tick on the existing _DELTA_BUCKETS ladder — the
    # queue never grows past depth and no delta is ever dropped. Only when
    # the merged delta would overflow the ladder's top bucket (which would
    # mint an unplanned compile) does the executor block for a slot, and
    # that wait is counted as stall time.

    def _tick_handles(self, out: tuple) -> tuple:
        """The device handles of one dispatched tick: what the in-flight
        queue holds, whose readiness marks the tick complete, and whose
        fetch the caller boundary may defer. Subclasses override to point
        at their own result surface (GnnStreamingScorer -> the GNN tick's
        outputs)."""
        return out

    def _tick_ready(self, handles: tuple) -> bool:
        h = handles[-1]
        if not hasattr(h, "is_ready"):
            return True
        try:
            return bool(h.is_ready())
        except RuntimeError:    # buffer already consumed: long complete
            return True

    def _retire_ready(self) -> None:
        """Pop completed ticks off the head of the in-flight queue. Their
        results are superseded without ever being fetched — exactly the
        per-tick readback the deferred-fetch boundary exists to avoid.
        Retirement is also where the host first OBSERVES a queued tick's
        device completion (the donated tick's ready event), so its
        TickSpan gets its ``execute`` stamp here — a host boundary, not
        an injected sync."""
        n0 = len(self._inflight)
        while self._inflight and self._tick_ready(self._inflight[0]):
            self._inflight.popleft()
            self._retire_meta(mark_execute=True)
            self.deferred_fetches += 1
        if n0 != len(self._inflight):
            obs_metrics.SERVE_DEFERRED_FETCHES.inc(
                float(n0 - len(self._inflight)))
        obs_metrics.SERVE_PIPELINE_INFLIGHT.set(
            float(len(self._inflight)), pack=self._scope_pack)

    def _retire_meta(self, mark_execute: bool = False) -> None:
        if not self._inflight_meta:
            return
        sp = self._inflight_meta.popleft()
        if sp is not None and mark_execute:
            sp.mark("execute")
        self.scope.finalize(sp)

    def _pending_delta_count(self) -> int:
        """Host-side delta entries a coalesced tick would carry, as the
        compiled tick will see them (bounds the merge against the delta
        ladder — per shard in graph-sharded mode)."""
        return self._pending_feat_bound() + len(self._dirty_rows)

    def tick_async(self) -> dict:
        """Pipelined tick submission for streaming drivers: flush pending
        deltas into one tick and enqueue it WITHOUT fetching, as long as a
        pipeline slot is free. On a full queue the deltas stay pending and
        merge into the next submitted tick (adaptive coalescing) instead
        of blocking — unless the merged delta would overflow the top
        _DELTA_BUCKETS bucket, in which case the executor stalls for the
        oldest tick (counted in ``stall_seconds``). Returns a small stats
        dict; results are fetched later via rescore()/serve()."""
        with self.serve_lock:
            return self._tick_async_locked()

    def absorb(self) -> dict:
        """Webhook-burst ingestion (graft-surge): drain the store
        journal(s) and submit ONE pipelined tick without fetching — the
        workflow worker calls this right after graph ingest, so the
        incident's deltas ride the bounded tick_async queue (coalescing
        on the delta ladder under bursts) and the device executes while
        the workflow's host steps continue. The verdict boundary then
        pays only a deferred newest-tick fetch (``serve(newest=True)``)
        instead of a synchronous per-incident dispatch+fetch round-trip.
        One lock acquisition covers sync + submit, so a concurrent
        absorb/serve cannot interleave between the journal drain and the
        tick that carries its deltas. NON-blocking by design: when a
        caller-boundary tick or fetch holds the serving state, absorb
        yields immediately (``busy``) instead of serializing webhook
        ingest behind device readbacks — the deltas stay in the journal
        and the contending boundary's own sync drains them.

        graft-storm bounds the backlog that yielding can build: every
        busy yield is counted (``aiops_serve_absorb_busy_total``), and
        once the unsynced store-journal backlog crosses
        ``settings.ingest_max_journal_backlog`` the yield escalates to a
        SYNCHRONOUS drain (blocking acquire, counted) — under a storm a
        busy serving loop can defer ingest, never let it grow without
        bound toward the store journal's truncation horizon."""
        if not self.serve_lock.acquire(blocking=False):
            self.absorb_busy += 1
            obs_metrics.SERVE_ABSORB_BUSY.inc()
            backlog = self._journal_backlog()
            if backlog <= self._max_journal_backlog:
                return {"dispatched": False, "coalesced": False,
                        "busy": True, "backlog": backlog}
            self.absorb_sync_drains += 1
            obs_metrics.SERVE_ABSORB_SYNC_DRAINS.inc()
            self.serve_lock.acquire()
        try:
            self.sync()
            return self._tick_async_locked()
        finally:
            self.serve_lock.release()

    def _journal_backlog(self) -> int:
        """Store-journal records not yet drained into the resident state
        (the backlog a busy-yielding absorb is deferring)."""
        return max(int(self.store.journal_seq) - int(self._synced_seq), 0)

    def _tick_async_locked(self) -> dict:
        """tick_async body; the caller holds ``serve_lock``."""
        self._retire_ready()
        # graft-storm degraded tier: while the ingest layer is in storm
        # mode, coalesce whenever ANY tick is already in flight (not just
        # on a full queue) — storm ticks merge toward the delta-ladder
        # top, one larger dispatch instead of many small ones. Host-side
        # only and bit-parity-preserving: coalescing is the same merge
        # the full-queue path already proves identical, and the caller
        # boundary (rescore/serve) still drains everything.
        if (obs_scope.STORM_FLAG["active"] and self._inflight
                and len(self._inflight) < self.pipeline_depth):
            pending = self._pending_delta_count()
            if pending < self._coalesce_bound:
                self.coalesced_ticks += 1
                self.storm_coalesced_ticks += 1
                self._scope_coalesced_since += 1
                self.scope.note_coalesced(pending)
                obs_metrics.SERVE_COALESCED_TICKS.inc()
                obs_metrics.SERVE_COALESCED_TICK_SIZE.set(float(pending))
                return {"dispatched": False, "coalesced": True,
                        "storm": True, "inflight": len(self._inflight),
                        "pending": pending}
        if len(self._inflight) >= self.pipeline_depth:
            pending = self._pending_delta_count()
            if pending < self._coalesce_bound:
                self.coalesced_ticks += 1
                self._scope_coalesced_since += 1
                self.scope.note_coalesced(pending)
                obs_metrics.SERVE_COALESCED_TICKS.inc()
                obs_metrics.SERVE_COALESCED_TICK_SIZE.set(float(pending))
                return {"dispatched": False, "coalesced": True,
                        "inflight": len(self._inflight),
                        "pending": pending}
            t0 = time.perf_counter()
            oldest = self._inflight.popleft()
            jax.block_until_ready(oldest[-1])
            stall = time.perf_counter() - t0
            self.stall_seconds += stall
            self.deferred_fetches += 1
            # the stall is queue pressure charged to the tick about
            # to dispatch; the drained tick's completion was just
            # host-observed, so stamp its execute boundary
            self.scope.note_queue_wait(stall)
            self._retire_meta(mark_execute=True)
            obs_metrics.SERVE_PIPELINE_STALL_SECONDS.inc(
                stall, pack=self._scope_pack)
            obs_metrics.SERVE_DEFERRED_FETCHES.inc()
        out = self.dispatch()
        self._inflight.append(self._tick_handles(out))
        self._inflight_meta.append(self._last_tick_span)
        self._last_tick_span = None
        obs_metrics.SERVE_PIPELINE_INFLIGHT.set(
            float(len(self._inflight)), pack=self._scope_pack)
        return {"dispatched": True, "coalesced": False,
                "inflight": len(self._inflight), "pending": 0}

    def _supersede_inflight(self) -> None:
        """A fresh caller-boundary tick makes every queued result stale:
        drop them all, unfetched (serve() fetches once per generation,
        not once per tick)."""
        if self._inflight:
            self.deferred_fetches += len(self._inflight)
            obs_metrics.SERVE_DEFERRED_FETCHES.inc(
                float(len(self._inflight)))
            self._inflight.clear()
        while self._inflight_meta:
            self._retire_meta()
        obs_metrics.SERVE_PIPELINE_INFLIGHT.set(
            0.0, pack=self._scope_pack)

    def serve(self, newest: bool = False) -> dict:
        """Coalesced sync + rescore for concurrent serving callers.

        With ``newest=True`` (the async workflow verdict path,
        graft-surge) the ticker prefers the deferred newest-tick fetch:
        when absorb() already drained the journal and submitted the tick,
        the generation costs one readback and ZERO fresh dispatches —
        see :meth:`rescore_newest` for the exact fallback conditions.

        The reference pays one Temporal activity chain per incident
        (activities.py:26-164); the fused tick already scores EVERY live
        incident, so concurrent callers must not each pay a serialized
        sync + device fetch (VERDICT r3 weak 3). Protocol: the first
        arrival becomes the ticker — it drains the journal and runs one
        rescore(); every caller that arrived before that tick started
        reads the shared result. Callers arriving while a tick is in
        flight wait for the NEXT tick (their store writes may postdate
        the running tick's sync). N concurrent incidents therefore cost
        at most 2 device fetches, and each caller's result is guaranteed
        to reflect its own prior store writes.
        """
        with self._serve_cv:
            need = self._serve_next_gen
            while self._serve_done_gen < need:
                if not self._serve_ticking:
                    gen = self._serve_next_gen
                    self._serve_next_gen = gen + 1
                    self._serve_ticking = True
                    break
                self._serve_cv.wait()
            else:
                return self._serve_result
        try:
            with self.serve_lock:
                self.sync()
                result = self.rescore_newest() if newest else self.rescore()
        except BaseException:
            with self._serve_cv:
                # roll back so a waiter can claim this generation; waiters
                # re-raise nothing — one of them simply becomes the ticker
                self._serve_next_gen = gen
                self._serve_ticking = False
                self._serve_cv.notify_all()
            raise
        with self._serve_cv:
            self._serve_done_gen = gen
            self._serve_result = result
            self._serve_ticking = False
            self._serve_cv.notify_all()
        return result

    def live_incidents(self) -> tuple[list[str], list[int]]:
        """(incident ids, their rows) for live incidents, in row order —
        before any arrival/closure this is exactly the snapshot's incident
        order, so results align with a fresh build_snapshot."""
        pairs = sorted((r, iid) for iid, r in self._inc_row_of.items())
        return [p[1] for p in pairs], [p[0] for p in pairs]

    def _drain_queue_wait(self) -> float:
        """Pre-dispatch drain of a FULL pipeline: the caller-boundary tick
        is about to dispatch behind ``depth`` unfinished ticks, and PR 5's
        split charged that wait into ``dispatch_seconds`` (and, once the
        device queue drained under the fetch, again into
        ``fetch_seconds``). Waiting for the oldest slot here — read-only,
        the total wall is unchanged — moves the wait into its own
        ``queue_wait_seconds`` bucket so neither window double-counts
        queue pressure. Returns the seconds waited (0.0 with a free
        slot)."""
        if len(self._inflight) < self.pipeline_depth:
            return 0.0
        t0 = time.perf_counter()
        jax.block_until_ready(self._inflight[0][-1])
        qw = time.perf_counter() - t0
        self.scope.note_queue_wait(qw)
        return qw

    def rescore(self) -> dict:
        """Caller-boundary tick + fetch. The dispatched tick reflects every
        pending delta (including ones coalesced by a full pipeline), so its
        result supersedes the whole in-flight queue — older results are
        dropped without a readback and exactly ONE device_get runs here.
        ``queue_wait_seconds`` is time blocked behind a full pipeline
        (see _drain_queue_wait); ``dispatch_seconds`` is host packing +
        enqueue (the part pipelining overlaps with device execution);
        ``fetch_seconds`` is the blocking device wait + device->host
        readback; ``device_seconds`` keeps the back-compat total — the
        sum of all three, the same window the old conflated split
        covered."""
        stats = {"feature_updates": len(self._pending_feat),
                 "structural_refresh": bool(self._dirty_rows),
                 "rebuilds": self.rebuilds,
                 "coalesced_ticks": self.coalesced_ticks,
                 "deferred_fetches": self.deferred_fetches,
                 "newest_fetch": False}
        queue_wait_s = self._drain_queue_wait()
        t1 = time.perf_counter()
        out = self.dispatch()
        span, self._last_tick_span = self._last_tick_span, None
        handles = self._tick_handles(out)
        self._supersede_inflight()
        dispatch_s = time.perf_counter() - t1
        return self._fetch_verdicts(handles, span, stats,
                                    queue_wait_s, dispatch_s)

    def rescore_newest(self) -> dict:
        """Deferred newest-tick verdict fetch (graft-surge): when the
        journal is drained and NO deltas are pending, the newest
        in-flight tick already reflects every store write — fetch ITS
        result handles (one device_get, older queued results dropped
        unfetched) without dispatching a fresh tick at all. This is the
        caller boundary the async workflow path hits in steady state:
        absorb() submitted the tick at webhook-ingest time, the device
        executed it while the workflow's host steps ran, and the verdict
        costs a readback only. Falls back to a full rescore() whenever
        deltas are pending or nothing is in flight (correctness first:
        a caller's store writes must always be reflected). Caller holds
        ``serve_lock`` (serve() does)."""
        if self._pending_delta_count() or not self._inflight:
            return self.rescore()
        stats = {"feature_updates": 0,
                 "structural_refresh": False,
                 "rebuilds": self.rebuilds,
                 "coalesced_ticks": self.coalesced_ticks,
                 "deferred_fetches": self.deferred_fetches,
                 "newest_fetch": True}
        handles = self._inflight.pop()          # newest submission
        span = self._inflight_meta.pop() if self._inflight_meta else None
        self._supersede_inflight()              # rest superseded, unfetched
        return self._fetch_verdicts(handles, span, stats, 0.0, 0.0)

    def _fetch_verdicts(self, handles, span, stats: dict,
                        queue_wait_s: float, dispatch_s: float) -> dict:
        """One blocking device_get over a tick's result handles → the
        caller-facing raw verdict dict. Shared tail of rescore() (fresh
        dispatch) and rescore_newest() (deferred newest-tick fetch);
        GnnStreamingScorer overrides it for its probs-only readback."""
        t2 = time.perf_counter()
        self._fault_point("fetch")
        if span is not None:
            # the block is the fetch's own device wait made explicit (a
            # host boundary the device_get below would cross anyway):
            # splits the span's execute window from the readback
            jax.block_until_ready(handles)
            span.mark("execute")
        fetched = jax.device_get(handles)
        fetch_s = time.perf_counter() - t2
        if span is not None:
            span.mark("fetch")
            exec_s = span.splits().get("execute", 0.0)
            self.scope.finalize(span, fetched=True)
            obs_scope.ROOFLINE.observe(self._scope_entry, self._scope_key,
                                       exec_s, pack=self._scope_pack)
        conds, matched, scores, top_idx, any_match, top_conf, top_score = (
            fetched)
        self.fetches += 1
        obs_metrics.SERVE_FETCHED_BYTES.inc(
            float(sum(a.nbytes for a in fetched)), path="rules_rescore")
        ids, rows = self.live_incidents()
        return {
            "incident_ids": tuple(ids),
            "conditions": conds[rows],
            "matched": matched[rows],
            "scores": scores[rows],
            "top_rule_index": top_idx[rows],
            "any_match": any_match[rows],
            "top_confidence": top_conf[rows],
            "top_score": top_score[rows],
            "queue_wait_seconds": queue_wait_s,
            "dispatch_seconds": dispatch_s,
            "fetch_seconds": fetch_s,
            "device_seconds": queue_wait_s + dispatch_s + fetch_s,
            "params_generation": self.params_generation,
            **stats,
        }
