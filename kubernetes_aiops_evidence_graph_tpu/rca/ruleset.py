"""The diagnosis rule set — single source of truth for both RCA backends.

Semantics are the reference rules engine's (rules_engine.py:16-191 rules,
:359-410 matching, :412-424 confidence; hypothesis_ranker.py:28-61 ranking),
with the reference's latent defects fixed (SURVEY.md §3.6 items 5-6):

* every condition type has a checker — ``multiple_pods_same_node``,
  ``pod_not_ready``, ``readiness_probe_failing`` and ``network_errors_high``
  are real conditions here, so all 10 rules can fire;
* machine-executable actions are separated from prose guidance
  (``action`` vs ``manual_steps``), so the policy engine is never asked to
  evaluate "Check application logs…" as an action type.

Because every condition carries a fixed strength (rules_engine.py:380-410)
and a rule only scores when ALL its conditions hold (:371), each rule's
confidence and final ranking score are compile-time constants — precomputed
here once. The runtime work of RCA is therefore entirely in deciding the
per-incident condition vector, which is exactly what the TPU backend
batches over the evidence graph.
"""
from __future__ import annotations

from dataclasses import dataclass, field
from enum import IntEnum

from ..models import ActionType, HypothesisCategory


class Cond(IntEnum):
    """Condition vocabulary. Index = column in the condition matrix."""
    WAITING_CRASHLOOP = 0
    WAITING_IMAGE_PULL = 1          # ImagePullBackOff | ErrImagePull | ImageInspectError
    TERMINATED_OOM = 2
    TERMINATED_CONFIG = 3           # ContainerCannotRun | CreateContainerConfigError
    RECENT_DEPLOY = 4
    NO_RECENT_DEPLOY = 5
    MEMORY_USAGE_HIGH = 6
    HPA_AT_MAX = 7
    LATENCY_HIGH = 8
    LOG_PATTERN_NETWORK = 9         # network | connection | timeout categories
    NODE_UNHEALTHY = 10
    MULTIPLE_PODS_SAME_NODE = 11    # >= 2 problem pods on one node
    POD_NOT_READY = 12              # not ready >= 300s
    READINESS_PROBE_FAILING = 13
    NETWORK_ERRORS_HIGH = 14        # network error count >= 10


NUM_CONDS = len(Cond)

# Fixed per-condition evidence strengths (rules_engine.py:380-410; the four
# new conditions get strengths consistent with their nearest reference kin).
COND_STRENGTH: dict[Cond, float] = {
    Cond.WAITING_CRASHLOOP: 0.9,
    Cond.WAITING_IMAGE_PULL: 0.9,
    Cond.TERMINATED_OOM: 0.9,
    Cond.TERMINATED_CONFIG: 0.9,
    Cond.RECENT_DEPLOY: 0.8,
    Cond.NO_RECENT_DEPLOY: 0.6,
    Cond.MEMORY_USAGE_HIGH: 0.85,
    Cond.HPA_AT_MAX: 0.75,
    Cond.LATENCY_HIGH: 0.7,
    Cond.LOG_PATTERN_NETWORK: 0.65,
    Cond.NODE_UNHEALTHY: 0.8,
    Cond.MULTIPLE_PODS_SAME_NODE: 0.8,
    Cond.POD_NOT_READY: 0.7,
    Cond.READINESS_PROBE_FAILING: 0.75,
    Cond.NETWORK_ERRORS_HIGH: 0.7,
}

# Thresholds referenced by condition evaluators (shared by both backends).
MULTIPLE_PODS_THRESHOLD = 2
POD_NOT_READY_SECONDS = 300
NETWORK_ERRORS_THRESHOLD = 10
MEMORY_HIGH_PCT = 90            # rules_engine.py:341-344
RECENT_DEPLOY_WINDOW_MIN = 30   # deploy_diff_collector.py recency window
PROBLEM_POD_RESTARTS = 3        # kubernetes_collector.py:269-285 heuristic

# Category ranking weights (hypothesis_ranker.py:28-40).
CATEGORY_WEIGHT: dict[HypothesisCategory, float] = {
    HypothesisCategory.RESOURCE_EXHAUSTION: 1.2,
    HypothesisCategory.BAD_DEPLOYMENT: 1.15,
    HypothesisCategory.CONFIGURATION_ERROR: 1.1,
    HypothesisCategory.INFRASTRUCTURE_ISSUE: 1.05,
    HypothesisCategory.DEPENDENCY_FAILURE: 1.0,
    HypothesisCategory.NETWORK_ISSUE: 0.95,
    HypothesisCategory.SCALING_ISSUE: 0.9,
    HypothesisCategory.SECURITY_ISSUE: 0.85,
    HypothesisCategory.EXTERNAL_DEPENDENCY: 0.8,
    HypothesisCategory.DATA_ISSUE: 0.75,
    HypothesisCategory.UNKNOWN: 0.5,
}


@dataclass(frozen=True)
class Rule:
    id: str
    name: str
    conditions: tuple[Cond, ...]
    category: HypothesisCategory
    hypothesis: str
    description: str
    confidence_base: float
    action: ActionType | None           # machine-executable remediation
    manual_steps: tuple[str, ...] = field(default=())

    @property
    def evidence_strength(self) -> float:
        """Mean condition strength when fully matched (rules_engine.py:377)."""
        return sum(COND_STRENGTH[c] for c in self.conditions) / len(self.conditions)

    @property
    def confidence(self) -> float:
        """confidence = base*0.6 + strength*0.4, *1.1 if >2 conds, cap 0.99,
        round 3 (rules_engine.py:412-424)."""
        conf = self.confidence_base * 0.6 + self.evidence_strength * 0.4
        if len(self.conditions) > 2:
            conf = min(conf * 1.1, 0.99)
        return round(conf, 3)

    @property
    def final_score(self) -> float:
        """Ranker score (hypothesis_ranker.py:44-63): confidence × category
        weight × support boost × signal boost, round 4."""
        score = self.confidence * CATEGORY_WEIGHT[self.category]
        support = len(self.conditions)
        score *= 1 + min(support, 5) * 0.05
        score *= 1 + self.evidence_strength * 0.2
        return round(score, 4)

    @property
    def recommended_actions(self) -> list[str]:
        out = [self.action.value] if self.action else []
        out.extend(self.manual_steps)
        return out


RULES: tuple[Rule, ...] = (
    Rule(
        id="crashloop_recent_deploy",
        name="Bad Deployment - CrashLoop",
        conditions=(Cond.WAITING_CRASHLOOP, Cond.RECENT_DEPLOY),
        category=HypothesisCategory.BAD_DEPLOYMENT,
        hypothesis="Recent deployment caused application crash",
        description=(
            "The application started crash looping immediately after a "
            "deployment; the new code or configuration likely prevents startup."
        ),
        confidence_base=0.90,
        action=ActionType.ROLLBACK_DEPLOYMENT,
        manual_steps=(
            "Check application logs for startup errors",
            "Review recent code changes in the deployment",
        ),
    ),
    Rule(
        id="crashloop_no_change",
        name="Runtime Error - CrashLoop",
        conditions=(Cond.WAITING_CRASHLOOP, Cond.NO_RECENT_DEPLOY),
        category=HypothesisCategory.EXTERNAL_DEPENDENCY,
        hypothesis="Application crashing due to external dependency or data issue",
        description=(
            "Crash looping with no recent deployment points at external "
            "dependencies, database state, or corrupted data."
        ),
        confidence_base=0.75,
        action=ActionType.RESTART_POD,
        manual_steps=(
            "Check external service connectivity",
            "Verify database connections",
            "Review application logs for dependency errors",
        ),
    ),
    Rule(
        id="oom_killed",
        name="Memory Exhaustion",
        conditions=(Cond.TERMINATED_OOM,),
        category=HypothesisCategory.RESOURCE_EXHAUSTION,
        hypothesis="Container killed due to memory limit exceeded",
        description=(
            "The container exceeded its memory limit: a leak, undersized "
            "limits, or a sudden usage spike."
        ),
        confidence_base=0.95,
        action=ActionType.RESTART_DEPLOYMENT,
        manual_steps=(
            "Increase memory limits if appropriate",
            "Check for memory leaks in application",
            "Review memory usage patterns",
        ),
    ),
    Rule(
        id="oom_high_memory",
        name="Memory Pressure",
        conditions=(Cond.MEMORY_USAGE_HIGH,),
        category=HypothesisCategory.RESOURCE_EXHAUSTION,
        hypothesis="Container approaching memory limit",
        description=(
            "Memory usage above 90% of the limit; at risk of OOMKill. Limits "
            "may be too low or there is a leak."
        ),
        confidence_base=0.80,
        action=None,
        manual_steps=(
            "Increase memory limits",
            "Investigate memory usage patterns",
            "Check for memory leaks",
        ),
    ),
    Rule(
        id="image_pull_failure",
        name="Image Pull Error",
        conditions=(Cond.WAITING_IMAGE_PULL,),
        category=HypothesisCategory.CONFIGURATION_ERROR,
        hypothesis="Failed to pull container image",
        description=(
            "The image cannot be pulled: bad tag, registry auth, or network "
            "problems."
        ),
        confidence_base=0.95,
        action=None,
        manual_steps=(
            "Verify image tag exists in registry",
            "Check imagePullSecrets configuration",
            "Verify registry authentication",
            "Check network connectivity to registry",
        ),
    ),
    Rule(
        id="node_failure_isolated",
        name="Node-Specific Issue",
        conditions=(Cond.MULTIPLE_PODS_SAME_NODE, Cond.NODE_UNHEALTHY),
        category=HypothesisCategory.INFRASTRUCTURE_ISSUE,
        hypothesis="Failures isolated to problematic node",
        description=(
            "Multiple failing pods share one node that reports unhealthy "
            "conditions; node infrastructure is the likely root cause."
        ),
        confidence_base=0.85,
        action=ActionType.CORDON_NODE,
        manual_steps=(
            "Migrate pods to healthy nodes",
            "Investigate node health",
            "Check node resource usage",
        ),
    ),
    Rule(
        id="hpa_maxed",
        name="Scaling Limit Reached",
        conditions=(Cond.HPA_AT_MAX, Cond.LATENCY_HIGH),
        category=HypothesisCategory.SCALING_ISSUE,
        hypothesis="HPA at maximum capacity with high latency",
        description=(
            "The autoscaler is at max replicas but latency remains high; the "
            "service needs more capacity than configured."
        ),
        confidence_base=0.80,
        action=ActionType.SCALE_REPLICAS,
        manual_steps=(
            "Increase HPA max replicas",
            "Review resource requests/limits",
            "Consider adding nodes to cluster",
        ),
    ),
    Rule(
        id="readiness_probe_failing",
        name="Readiness Probe Failure",
        conditions=(Cond.POD_NOT_READY, Cond.READINESS_PROBE_FAILING),
        category=HypothesisCategory.DEPENDENCY_FAILURE,
        hypothesis="Pods failing readiness probe",
        description=(
            "Pods never become ready because the readiness probe fails — the "
            "app cannot serve traffic, usually a dependency issue."
        ),
        confidence_base=0.75,
        action=None,
        manual_steps=(
            "Check application health endpoints",
            "Verify database connections",
            "Check external service dependencies",
            "Review probe configuration",
        ),
    ),
    Rule(
        id="config_error",
        name="Configuration Error",
        conditions=(Cond.TERMINATED_CONFIG,),
        category=HypothesisCategory.CONFIGURATION_ERROR,
        hypothesis="Container configuration error",
        description=(
            "The container cannot run due to configuration: missing volumes, "
            "invalid env vars, or security context problems."
        ),
        confidence_base=0.90,
        action=None,
        manual_steps=(
            "Check ConfigMap and Secret references",
            "Verify volume mounts",
            "Review container security context",
            "Check environment variable configurations",
        ),
    ),
    Rule(
        id="network_error",
        name="Network Connectivity Issue",
        conditions=(Cond.LOG_PATTERN_NETWORK, Cond.NETWORK_ERRORS_HIGH),
        category=HypothesisCategory.NETWORK_ISSUE,
        hypothesis="Network connectivity problems",
        description=(
            "The application reports network connectivity errors: DNS, "
            "service mesh, or network policy restrictions."
        ),
        confidence_base=0.70,
        action=None,
        manual_steps=(
            "Check DNS resolution",
            "Verify network policies",
            "Check service mesh configuration",
            "Test connectivity to external services",
        ),
    ),
)

NUM_RULES = len(RULES)
RULE_INDEX = {r.id: i for i, r in enumerate(RULES)}

# Unknown fallback (rules_engine.py:426-447): confidence 0.3, unknown
# category; ranker: 0.3 * 0.5 * 1 * 1 = 0.15.
UNKNOWN_CONFIDENCE = 0.3
UNKNOWN_FINAL_SCORE = round(UNKNOWN_CONFIDENCE * CATEGORY_WEIGHT[HypothesisCategory.UNKNOWN], 4)
UNKNOWN_ACTIONS = (
    "Review application logs",
    "Check recent deployments",
    "Verify external dependencies",
    "Escalate to engineering team",
)
