"""Roofline accounting for the device passes (VERDICT r4 ask 1).

Converts the bench's per-pass times from unanchored milliseconds into
hardware-relative statements: bytes touched, FLOPs, achieved HBM GB/s, and
the fraction of the pass explained by the machine's roofline. Three parts:

1. **Analytic cost models** — minimum HBM traffic and FLOPs of the
   evidence fold (`tpu_backend._aggregate` + `finish_scores`) and of one
   GNN message-passing layer (`gnn._message_pass`), as closed-form
   functions of the padded shapes. These are *lower bounds* on traffic:
   XLA may materialize intermediates (the [Pi, chunk, Wr] one-hot, the
   masked gather rows), so achieved-GB/s computed from them is itself a
   lower bound on what the chip actually streamed.

2. **Measured anchors** — the chip's achievable HBM bandwidth (chained
   big-buffer elementwise op) and bf16 matmul throughput (chained
   [n,n]@[n,n]), both via the K-pass slope method that the tunnel forces
   (see bench.py: `block_until_ready` does not wait here and every fresh
   fetch costs a fixed ~64-75 ms RTT, so single-pass walls measure the
   tunnel). Anchors are measured, not copied from the datasheet; the
   datasheet ceilings (v5e-1: 819 GB/s HBM, 197 bf16 TFLOP/s) are
   reported alongside for reference.

3. **Device-only vs dispatch decomposition** — `lax.fori_loop` with a
   *traced* trip count runs k scoring passes inside one jitted call, so
   per-pass time from the loop slope contains zero per-pass
   dispatch/tunnel cost (and growing k needs no recompile). The
   chained-dispatch slope (bench_rca's headline method) minus the loop
   slope is the per-dispatch overhead a co-located host would mostly not
   pay. The loop body carries the top_score chain into an input of the
   fold (reference cost anchor: the per-incident loop of the reference's
   rules_engine.py:200-234), so results stay bit-identical and no pass
   can be elided or hoisted.
"""
from __future__ import annotations

import time
from functools import partial

import jax
import jax.numpy as jnp

from .tpu_backend import DeviceBatch, finish_scores, _aggregate

# v5e-1 datasheet ceilings, reported alongside the measured anchors
V5E_HBM_GBPS = 819.0
V5E_BF16_TFLOPS = 197.0


# -- analytic cost models -------------------------------------------------

def fold_accounting(pi: int, width: int, pair_width: int, dim: int,
                    num_conds: int | None = None,
                    num_rules: int | None = None) -> dict:
    """Minimum HBM bytes + FLOPs of one `_score_device` pass.

    Traffic model (f32 = 4 bytes):
      reads  — gathered feature rows Pi*W*DIM (the fold reads the row for
               every live slot; padding rows gather row 0 which stays hot
               in cache, so live-slot traffic is the floor), slot tables
               ev_idx + ev_pair_slot Pi*W*2, counts Pi;
      writes — folded counts Pi*DIM, pair counts Pi*Wr, score outputs
               Pi*(C + 3R + 4).
    FLOPs: mask build + masked multiply-add fold 3*Pi*W*DIM, one-hot pair
    contraction 2*Pi*W*Wr, condition thresholds ~8*Pi*C, rule matmul
    2*Pi*C*R, scoring tail ~6*Pi*R.
    """
    from .ruleset import NUM_CONDS, NUM_RULES
    c = num_conds if num_conds is not None else NUM_CONDS
    r = num_rules if num_rules is not None else NUM_RULES
    reads = pi * width * dim * 4 + pi * width * 2 * 4 + pi * 4
    writes = pi * dim * 4 + pi * pair_width * 4 + pi * (c + 3 * r + 4) * 4
    flops = (3 * pi * width * dim + 2 * pi * width * pair_width
             + 8 * pi * c + 2 * pi * c * r + 6 * pi * r)
    return {"bytes": reads + writes, "flops": flops,
            "reads": reads, "writes": writes}


def gnn_layer_accounting(pn: int, e: int, hidden: int,
                         bucketed: bool = False,
                         compute_bytes: int = 4) -> dict:
    """Minimum HBM bytes + FLOPs of one GNN message-passing layer.

    ``bucketed=False`` — the reference transform-then-gather mapping
    (`gnn._message_pass`): all R = NUM_RELS transformed copies computed
    densely, each edge gathers its rel-specific source row, one [E, H]
    segment-sum.
      reads  — h for the two matmuls + residual 3*Pn*H, weights
               R*H*H + H*H + H, transformed-copy gather E*H (from the
               [Pn*R, H] table), edge mask + rel 2E, inv_deg Pn;
      writes — transformed copies Pn*R*H, scatter accumulator Pn*H (plus
               E*H read-modify-write traffic, counted once as E*H), layer
               output Pn*H.
      FLOPs  — relation einsum 2*Pn*R*H*H, w_self matmul 2*Pn*H*H, mask
               multiply E*H, scatter adds E*H, degree scale Pn*H,
               bias+relu+residual 3*Pn*H.

    ``bucketed=True`` — the relation-bucketed mapping
    (`gnn._message_pass_bucketed`): per-relation slices gather [E_r, H]
    source rows, one [H, H] matmul each, per-slice segment-sums into one
    [N, H] accumulator. No [Pn, R, H] term anywhere — edge traffic scales
    with E (here ``e`` = the SUM of padded slice capacities,
    snapshot.rel_offsets[-1]).
      reads  — source-row gather E*H, h for self matmul + residual
               2*Pn*H, weights (R+1)*H*H + H, messages re-read by the
               scatter E*H, src+dst indices 2E, mask E, inv_deg Pn;
      writes — messages E*H, scatter accumulator Pn*H (RMW counted once
               as E*H), layer output Pn*H.
      FLOPs  — slice matmuls 2*E*H*H, w_self matmul 2*Pn*H*H, mask
               multiply E*H, scatter adds E*H, degree scale + bias +
               relu + residual 4*Pn*H.

    ``compute_bytes`` scales the matmul-OPERAND traffic terms (gathered
    rows, weights, message writes/reads) for the bf16 compute path (pass
    2); accumulator/output/index traffic stays f32/int32 at 4 bytes.
    """
    from .gnn import NUM_RELS as r
    if bucketed:
        cb = compute_bytes
        reads = (e * hidden * cb + 2 * pn * hidden * 4
                 + ((r + 1) * hidden * hidden + hidden) * cb
                 + e * hidden * cb + 3 * e * 4 + pn * 4)
        writes = (e * hidden * cb + (e + 2 * pn) * hidden * 4)
        flops = (2 * e * hidden * hidden + 2 * pn * hidden * hidden
                 + 2 * e * hidden + 4 * pn * hidden)
        return {"bytes": reads + writes, "flops": flops,
                "reads": reads, "writes": writes}
    reads = (3 * pn * hidden + r * hidden * hidden + hidden * hidden
             + hidden + e * hidden + 2 * e + pn) * 4
    writes = (pn * r * hidden + 2 * pn * hidden + e * hidden) * 4
    flops = (2 * pn * r * hidden * hidden + 2 * pn * hidden * hidden
             + 2 * e * hidden + pn * hidden + 3 * pn * hidden)
    return {"bytes": reads + writes, "flops": flops,
            "reads": reads, "writes": writes}


# -- measured anchors -----------------------------------------------------

def _slope(run, k1: int, k2: int, repeats: int = 2) -> float:
    """Per-pass seconds from two chained-run lengths (tunnel-safe)."""
    t1 = min(run(k1) for _ in range(repeats))
    t2 = min(run(k2) for _ in range(repeats))
    return max((t2 - t1) / (k2 - k1), 1e-9)


@partial(jax.jit, static_argnames=("k",))
def _scan_stream(x, k: int):
    """k chained read+write passes over x inside ONE jitted call — the
    carry dependency defeats both elision and loop-invariant hoisting, and
    a single dispatch + fetch means zero per-pass tunnel cost."""
    return jax.lax.scan(lambda c, _: (c * 1.0000001 + 1e-12, None),
                        x, None, length=k)[0]


def measure_hbm_gbps(mib: int = 512, k1: int = 4, k2: int = 32) -> float:
    """Achievable HBM bandwidth: scanned `x = x * a + b` over a ~`mib` MiB
    f32 buffer. Each pass reads + writes the buffer once → 2 * size
    bytes."""
    n = mib * (1 << 20) // 4
    x0 = jnp.ones((n,), jnp.float32)

    def run(k: int) -> float:
        t0 = time.perf_counter()
        jax.device_get(_scan_stream(x0, k=k)[0])
        return time.perf_counter() - t0

    run(k1)   # warm both compiles before timing
    run(k2)
    per_pass = _slope(run, k1, k2)
    return 2 * n * 4 / per_pass / 1e9


@partial(jax.jit, static_argnames=("k",))
def _scan_matmul(a, k: int):
    return jax.lax.scan(lambda c, _: (c @ a, None), a, None, length=k)[0]


def measure_matmul_tflops(n: int = 8192, k1: int = 2, k2: int = 10) -> float:
    """Achievable bf16 matmul throughput via the same scanned slope
    ([n,n]@[n,n] = 2n³ FLOPs per pass; n=8192 → 1.1 TFLOP ≈ 5.6 ms at
    the v5e-1 ceiling, comfortably above launch noise)."""
    a = jnp.ones((n, n), jnp.bfloat16)

    def run(k: int) -> float:
        t0 = time.perf_counter()
        jax.device_get(_scan_matmul(a, k=k)[0, 0])
        return time.perf_counter() - t0

    run(k1)
    run(k2)
    per_pass = _slope(run, k1, k2)
    return 2 * n ** 3 / per_pass / 1e12


def measure_fetch_rtt_ms(samples: int = 5) -> float:
    """Cost of ONE synchronous fetch of a fresh tiny result — on the dev
    tunnel this is the ~64-75 ms RTT; co-located hosts measure µs. Each
    sample perturbs the input so the result is never cached."""
    f = jax.jit(lambda x: x * 2.0)
    x = jnp.ones((8,), jnp.float32)
    jax.device_get(f(x))  # warm compile
    times = []
    for i in range(samples):
        y = f(x + float(i))
        t0 = time.perf_counter()
        jax.device_get(y)
        times.append(time.perf_counter() - t0)
    times.sort()
    return times[len(times) // 2] * 1e3


# -- device-only scoring time (scan: zero per-pass dispatch) --------------

@partial(jax.jit, static_argnames=("padded_incidents", "pair_width"))
def _loop_score(features, ev_idx, ev_cnt, ev_pair_slot, k,
                padded_incidents: int, pair_width: int):
    """k chained scoring passes inside ONE jitted call, k TRACED (a
    fori_loop, so any k reuses the same executable — the adaptive slope
    below can grow k until the timing delta towers over tunnel noise
    without recompiling).

    The carry (previous pass's top_score) perturbs an INPUT of the fold:
    ev_cnt + int(min(top_score, 0)). Real scores are always >= 0 so the
    perturbation is exactly zero and results are bit-identical to k
    independent passes (asserted in tests) — but the compiler cannot
    prove that, so the fold is loop-VARIANT and cannot be hoisted out of
    the loop (feeding the chain in *after* the fold, as dispatch() does,
    lets XLA's loop-invariant code motion compute the whole fold once —
    measured: a near-zero 'per-pass time'). The perturbed ev_cnt is a
    [Pi] elementwise add, so the trick costs ~nothing."""

    def one_pass(chain):
        cnt_k = ev_cnt + jnp.minimum(chain, 0.0).astype(jnp.int32)
        counts, per_row_max = _aggregate(
            features, ev_idx, cnt_k, ev_pair_slot,
            padded_incidents, pair_width)
        return finish_scores(counts, per_row_max, padded_incidents)

    outs0 = one_pass(jnp.zeros((padded_incidents,), jnp.float32))
    # remaining k-1 passes carry the full output tuple so the LAST pass's
    # outputs come back regardless of k
    return jax.lax.fori_loop(1, k, lambda _, outs: one_pass(outs[6]), outs0)


def measure_scan_per_pass_s(batch: DeviceBatch, device_args: tuple,
                            k1: int = 8, min_delta_s: float = 0.05,
                            k_cap: int = 1 << 17) -> float:
    """Device-only per-pass seconds of the scoring pass: slope over two
    loop lengths, each a single dispatch + single fetch, so neither the
    per-pass dispatch cost nor the fetch RTT is in the slope. k2 grows
    (same executable — k is traced) until the k2-vs-k1 wall delta is
    ≥ `min_delta_s`, i.e. well above tunnel RTT jitter, so even a ~µs
    device pass resolves."""

    def run(k: int) -> float:
        t0 = time.perf_counter()
        outs = _loop_score(
            *device_args, jnp.int32(k),
            padded_incidents=batch.padded_incidents,
            pair_width=batch.pair_width)
        jax.device_get(outs[6][0])
        return time.perf_counter() - t0

    run(k1)  # warm the single executable
    t1 = min(run(k1) for _ in range(3))
    k2 = max(8 * k1, 64)
    while True:
        t2 = min(run(k2) for _ in range(2))
        if t2 - t1 >= min_delta_s or k2 >= k_cap:
            return max((t2 - t1) / (k2 - k1), 1e-9)
        k2 *= 4


def measure_gnn_forward_per_pass_s(params, snapshot, k1: int = 4,
                                   k2: int = 16, bucketed: bool = False,
                                   compute_dtype: str | None = None,
                                   pallas: bool = False) -> float:
    """Device-only per-forward seconds of the full GNN (all layers), via a
    scanned forward whose input features are scaled by
    ``1 + mean_logit * 1e-38`` — exactly 1.0 in f32 (the product
    underflows the 2^-24 ulp at 1.0), so results are unchanged, but the
    compiler cannot prove it, which makes every layer loop-variant (no
    hoisting; see _scan_score). Only the degree normalization (an O(E)
    add) is invariant and hoistable — noise next to the matmuls.

    ``bucketed=True`` times the relation-bucketed kernel on the
    snapshot's (rel, dst) layout (with the optional bf16
    ``compute_dtype``); ``pallas=True`` (implies bucketed) times the
    tiled VMEM-resident Pallas tier instead — the bench's
    pallas-vs-XLA A/B rides this flag; False times the
    transform-then-gather reference on the same arrays — all variants
    are directly comparable because they consume identical inputs."""
    from . import gnn
    if pallas:
        bucketed = True
    b = gnn.snapshot_batch(snapshot)
    args = tuple(jnp.asarray(b[key]) for key in (
        "features", "node_kind", "node_mask", "edge_src", "edge_dst",
        "edge_rel", "edge_mask", "incident_nodes"))

    offs = tuple(b.get("rel_offsets") or ()) if bucketed else None
    if bucketed and not offs:
        raise ValueError("bucketed=True needs a relation-bucketed snapshot")
    sorted_by_dst = (not bucketed) and gnn.edges_sorted_by_dst(b["edge_dst"])
    slices_sorted = bool(offs) and gnn.slices_sorted_by_dst(
        b["edge_dst"], offs)

    @partial(jax.jit, static_argnames=("k", "sorted_", "offs", "ss", "cd",
                                       "pal"))
    def scan_fwd(params, features, node_kind, node_mask, edge_src, edge_dst,
                 edge_rel, edge_mask, incident_nodes, k: int, sorted_: bool,
                 offs, ss: bool, cd, pal: bool):
        def body(carry, _):
            f = features * (1.0 + carry * 1e-38)
            logits = gnn.forward(params, f, node_kind, node_mask,
                                 edge_src, edge_dst, edge_rel, edge_mask,
                                 incident_nodes, sorted_by_dst=sorted_,
                                 rel_offsets=offs, slices_sorted=ss,
                                 compute_dtype=cd, pallas=pal)
            return logits.mean(), None
        last, _ = jax.lax.scan(body, jnp.float32(0.0), None, length=k)
        return last

    def run(k: int) -> float:
        t0 = time.perf_counter()
        out = scan_fwd(params, *args, k=k, sorted_=sorted_by_dst,
                       offs=offs, ss=slices_sorted, cd=compute_dtype,
                       pal=pallas)
        jax.device_get(out)
        return time.perf_counter() - t0

    run(k1)
    run(k2)
    return _slope(run, k1, k2)


# -- assembly -------------------------------------------------------------

def roofline_record(bytes_touched: int, flops: int, per_pass_s: float,
                    bw_gbps: float, tflops: float) -> dict:
    """Per-pass achieved rates + the roofline-explained share of the time.

    roofline_ms is the time the pass WOULD take if it ran at the measured
    anchor rates (max of the bandwidth term and the compute term);
    roofline_pct = that floor / the measured pass time. 100% = at the
    hardware ceiling; small % = the pass is dominated by per-kernel
    launch/sync overheads rather than streaming or FLOPs — i.e. headroom
    lives in batching/fusion, not in a faster kernel."""
    bw_s = bytes_touched / (bw_gbps * 1e9)
    fl_s = flops / (tflops * 1e12) if tflops > 0 else 0.0
    floor_s = max(bw_s, fl_s)
    return {
        "bytes_per_pass": int(bytes_touched),
        "flops_per_pass": int(flops),
        "achieved_gbps": round(bytes_touched / per_pass_s / 1e9, 2),
        "achieved_gflops": round(flops / per_pass_s / 1e9, 2),
        "roofline_floor_ms": round(floor_s * 1e3, 5),
        "roofline_pct": round(100.0 * floor_s / per_pass_s, 2),
        "bound": "bandwidth" if bw_s >= fl_s else "compute",
    }
