"""Learned RCA backend — rca_backend="gnn".

Third backend behind the plugin seam (alongside the CPU oracle and the
TPU rules pass): scores every incident in a GraphSnapshot with the trained
GNN (rca/gnn.py), returning the same raw-dict / RCAResult surface as
TpuRcaBackend so the workflow and API are backend-agnostic. Parameters come
from an orbax checkpoint (settings.gnn_checkpoint, written by rca/train.py)
or are injected directly.
"""
from __future__ import annotations

from functools import partial
from uuid import uuid4

import numpy as np

import jax

from ..models import Hypothesis, HypothesisSource, RCAResult
from . import gnn
from .ruleset import NUM_RULES, RULES, UNKNOWN_CONFIDENCE
from .tpu_backend import _incident_uuid


def _shipped_checkpoint() -> str | None:
    """The repo ships an evaluated checkpoint (checkpoints/gnn; metrics in
    GNN_EVAL.json: relation-aware model, 98.3% top-1 on a 240-incident
    class-balanced holdout — 99.6% on the incidents whose label is
    derivable at all (the remainder are indistinguishable-twin incidents,
    see holdout_crosscheck) — trained on 130 base + 130 augmented
    episodes across 96-2048-pod clusters, 100% at 4k-8k-pod scale) so
    rca_backend=gnn works without prior training. Repo checkouts only —
    the checkpoint is not wheel package-data, so pip installs must set
    KAEG_GNN_CHECKPOINT (or train their own via rca/train.py)."""
    from pathlib import Path
    p = Path(__file__).resolve().parents[2] / "checkpoints" / "gnn"
    return str(p) if p.is_dir() else None


class CheckpointError(ValueError):
    """A checkpoint could not be loaded or fails the serving contract
    (corrupt files, legacy pre-relation-aware layout, missing keys).
    ValueError subclass so existing callers' except clauses still match;
    the workflow worker catches it to fall back to the rules serving tier
    instead of crashing the worker — with graft-evolve hot-swapping
    checkpoints in and out, load failures are an operational event, not a
    programming error."""


def load_validated_checkpoint(path: str) -> gnn.Params:
    """Load an orbax checkpoint and validate it against the serving
    model contract, normalizing every failure mode — unreadable/corrupt
    files (orbax raises a zoo of exception types), a payload that is not
    a params tree, or the legacy pre-relation-aware layout — into one
    clear :class:`CheckpointError`. The single load path for the backend,
    the streaming scorer, and the online-learning loop's swap/recovery
    reloads (hot swap multiplies how often checkpoints are loaded, so
    this error path is load-bearing, not defensive)."""
    from .train import load_checkpoint
    try:
        restored = load_checkpoint(path)
    except Exception as exc:  # catch-and-rethrow: orbax load failures span
        # OSError/ValueError/KeyError/TypeError and plugin-specific types;
        # all mean the same operational thing, normalized below
        raise CheckpointError(
            f"checkpoint at {path} is unreadable ({type(exc).__name__}: "
            f"{exc}): retrain with rca/train.py or point "
            "KAEG_GNN_CHECKPOINT at a valid checkpoint") from exc
    params = (restored or {}).get("params") if isinstance(restored, dict) \
        else None
    if not isinstance(params, dict) or "embed_w" not in params:
        raise CheckpointError(
            f"checkpoint at {path} does not contain a GNN params tree "
            "(expected a {'params': {...}} orbax payload written by "
            "rca/train.py)")
    layers = params.get("layers") or []
    if layers and "w_rel" not in layers[0]:
        # pre-relation-aware checkpoints (round ≤4: per-layer "w_msg")
        # would otherwise surface as a bare KeyError deep inside jit
        # tracing (code-review r5)
        raise CheckpointError(
            f"checkpoint at {path} predates the relation-aware GNN "
            "(layers carry 'w_msg', expected 'w_rel'): retrain with "
            "rca/train.py or point KAEG_GNN_CHECKPOINT at a current "
            "checkpoint")
    return params


class GnnRcaBackend:
    name = "gnn"

    def __init__(self, params: gnn.Params | None = None,
                 settings=None) -> None:
        from ..config import get_settings
        cfg = settings or get_settings()
        if params is None:
            path = cfg.gnn_checkpoint or _shipped_checkpoint()
            if not path:
                raise CheckpointError(
                    "rca_backend=gnn needs trained parameters: set "
                    "KAEG_GNN_CHECKPOINT (written by rca/train.py) or pass "
                    "params=")
            params = load_validated_checkpoint(path)
        self.params = params
        # kernel selection is per-batch via gnn.forward_batch: snapshots
        # carry the relation-bucketed layout (rel_offsets) and take the
        # bucketed kernel unless settings.gnn_bucketed turns it off (the
        # reference transform-then-gather escape hatch); layout promises
        # (per-slice / global dst sort) are host-checked per call — an
        # O(E) scan, noise next to tensorization.
        self._bucketed = bool(getattr(cfg, "gnn_bucketed", True))
        self._compute_dtype = getattr(cfg, "gnn_compute_dtype", "") or None
        # settings.gnn_pallas promotes snapshot scoring to the tiled
        # VMEM-resident Pallas kernel (ops/pallas_segment.py) — forward
        # only, bit-identical to the bucketed kernel; training and the
        # streaming tick stay on the XLA path
        self._pallas = bool(getattr(cfg, "gnn_pallas", False))

    def score_snapshot(self, snapshot) -> dict:
        """Same keys as TpuRcaBackend.score_snapshot where meaningful."""
        b = gnn.snapshot_batch(snapshot)
        logits = gnn.forward_batch(self.params, b, bucketed=self._bucketed,
                                   compute_dtype=self._compute_dtype,
                                   pallas=self._pallas)
        probs = np.asarray(jax.device_get(jax.nn.softmax(logits, axis=-1)))
        n = snapshot.num_incidents
        pred = probs.argmax(axis=-1)
        return {
            "incident_ids": snapshot.incident_ids,
            "probs": probs[:n],
            "top_rule_index": pred[:n],                      # NUM_RULES = unknown
            "any_match": (pred != NUM_RULES)[:n],
            "top_confidence": probs.max(axis=-1)[:n],
        }

    def results(self, snapshot, raw: dict | None = None,
                top_k: int = 3) -> list[RCAResult]:
        raw = raw or self.score_snapshot(snapshot)
        out: list[RCAResult] = []
        for i, inc_id in enumerate(raw["incident_ids"]):
            uid = _incident_uuid(inc_id)
            order = np.argsort(raw["probs"][i])[::-1][:top_k]
            hyps: list[Hypothesis] = []
            # argmax == unknown  ⇒  any_match is False: the incident gets the
            # unknown hypothesis, not a low-probability rule promoted to top-1
            if int(order[0]) != NUM_RULES:
                ranked = [c for c in order
                          if c != NUM_RULES and raw["probs"][i][c] > 0.0]
                for rank, cls in enumerate(ranked, start=1):
                    conf = float(raw["probs"][i][cls])
                    rule = RULES[int(cls)]
                    hyps.append(Hypothesis(
                        id=uuid4(), incident_id=uid, category=rule.category,
                        title=rule.name, description=rule.description,
                        confidence=min(conf, 0.99), final_score=conf, rank=rank,
                        recommended_actions=rule.recommended_actions,
                        rule_id=rule.id, backend="gnn",
                        generated_by=HypothesisSource.GNN,
                    ))
            if not hyps:
                from .cpu_backend import _unknown_hypothesis
                from .signals import Signals
                h = _unknown_hypothesis(uid, Signals())
                h.backend = "gnn"
                h.generated_by = HypothesisSource.GNN
                h.confidence = UNKNOWN_CONFIDENCE
                hyps = [h]
            out.append(RCAResult(
                incident_id=uid, hypotheses=hyps, top_hypothesis=hyps[0],
                rules_matched=[h.rule_id for h in hyps if h.rule_id != "unknown"],
                backend="gnn",
            ))
        return out
