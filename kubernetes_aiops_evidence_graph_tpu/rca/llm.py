"""Optional LLM hypothesis enrichment.

Parity with the reference LLMSummarizer (llm_summarizer.py:22-190): enhances
the top-3 hypotheses with reasoning / additional steps / alternatives via a
provider backend (gemini | openai | ollama REST), JSON extracted by brace
scan, evidence summarized as a ≤20-bullet list. Failures always fall back
to the rules-only hypotheses (activities.py:144-152). Provider "none"
disables enrichment (this environment has zero egress).
"""
from __future__ import annotations

import json
import urllib.request
from typing import Iterable

from ..config import Settings, get_settings
from ..models import Hypothesis, HypothesisSource, Incident
from ..observability import get_logger

log = get_logger("llm")


def _extract_json(text: str) -> dict | None:
    """Brace-scan extraction (llm_summarizer.py:117-126)."""
    start = text.find("{")
    if start < 0:
        return None
    depth = 0
    for i in range(start, len(text)):
        if text[i] == "{":
            depth += 1
        elif text[i] == "}":
            depth -= 1
            if depth == 0:
                try:
                    return json.loads(text[start:i + 1])
                except json.JSONDecodeError:
                    return None
    return None


def _summarize_evidence(evidence: Iterable[dict], limit: int = 20) -> str:
    bullets = []
    for ev in list(evidence)[:limit]:
        data = ev.get("data", {}) or {}
        key = (data.get("waiting_reason") or data.get("terminated_reason")
               or data.get("query_name") or ev.get("evidence_type"))
        bullets.append(f"- {ev.get('evidence_type')}: {ev.get('entity_name')} ({key})")
    return "\n".join(bullets)


class LLMSummarizer:
    def __init__(self, settings: Settings | None = None) -> None:
        self.settings = settings or get_settings()

    @property
    def enabled(self) -> bool:
        return self.settings.llm_provider not in ("", "none")

    def enhance_hypotheses(
        self,
        incident: Incident,
        hypotheses: list[Hypothesis],
        evidence: list[dict],
        top_n: int = 3,
    ) -> list[Hypothesis]:
        if not self.enabled:
            return hypotheses
        out = list(hypotheses)
        for i, h in enumerate(out[:top_n]):
            try:
                prompt = self._build_prompt(incident, h, evidence)
                raw = self._complete(prompt)
                parsed = _extract_json(raw or "")
                if not parsed:
                    continue
                h.reasoning = parsed.get("reasoning") or h.reasoning
                extra = parsed.get("additional_steps") or []
                h.recommended_actions = list(h.recommended_actions) + [
                    s for s in extra if s not in h.recommended_actions]
                if parsed.get("enhanced_description"):
                    h.description = parsed["enhanced_description"]
                h.why_not_notes = parsed.get("alternatives") or h.why_not_notes
                h.generated_by = HypothesisSource.HYBRID
            except Exception as exc:  # graft-audit: allow[broad-except] fall back silently (activities.py:144-152)
                log.warning("llm_enhancement_failed", hypothesis=h.rule_id,
                            error=str(exc))
        return out

    def _build_prompt(self, incident: Incident, h: Hypothesis,
                      evidence: list[dict]) -> str:
        return (
            "You are an SRE assistant. Given this incident and hypothesis, "
            "reply with JSON {\"reasoning\": str, \"additional_steps\": [str], "
            "\"alternatives\": str, \"enhanced_description\": str}.\n"
            f"Incident: {incident.title} (severity {incident.severity.value}, "
            f"namespace {incident.namespace}, service {incident.service})\n"
            f"Hypothesis: {h.title} — {h.description} "
            f"(confidence {h.confidence})\n"
            f"Evidence:\n{_summarize_evidence(evidence)}"
        )

    # -- providers (llm_summarizer.py:92-190) -----------------------------

    def _complete(self, prompt: str) -> str | None:
        provider = self.settings.llm_provider
        if provider == "gemini":
            return self._gemini(prompt)
        if provider == "openai":
            return self._openai(prompt)
        if provider == "ollama":
            return self._ollama(prompt)
        raise ValueError(f"unknown llm provider {provider!r}")

    def _post_json(self, url: str, payload: dict, headers: dict) -> dict:
        req = urllib.request.Request(
            url, data=json.dumps(payload).encode(),
            headers={"Content-Type": "application/json", **headers})
        with urllib.request.urlopen(req, timeout=30) as resp:  # noqa: S310
            return json.loads(resp.read())

    def _gemini(self, prompt: str) -> str | None:
        model = self.settings.llm_model or "gemini-1.5-flash"
        body = self._post_json(
            f"https://generativelanguage.googleapis.com/v1beta/models/"
            f"{model}:generateContent?key={self.settings.llm_api_key}",
            {"contents": [{"parts": [{"text": prompt}]}]}, {})
        candidates = body.get("candidates") or []
        if candidates:
            parts = candidates[0].get("content", {}).get("parts", [])
            return "".join(p.get("text", "") for p in parts)
        return None

    def _openai(self, prompt: str) -> str | None:
        body = self._post_json(
            "https://api.openai.com/v1/chat/completions",
            {"model": self.settings.llm_model or "gpt-4o-mini",
             "messages": [{"role": "user", "content": prompt}]},
            {"Authorization": f"Bearer {self.settings.llm_api_key}"})
        choices = body.get("choices") or []
        return choices[0]["message"]["content"] if choices else None

    def _ollama(self, prompt: str) -> str | None:
        body = self._post_json(
            "http://localhost:11434/api/generate",
            {"model": self.settings.llm_model or "llama3", "prompt": prompt,
             "stream": False}, {})
        return body.get("response")
