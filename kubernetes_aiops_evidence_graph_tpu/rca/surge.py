"""graft-surge: multi-tenant packing — many cluster stores, ONE resident
serving state, cross-tenant verdicts in one device pass.

The per-tenant serving story until now was one resident
:class:`~..rca.streaming.StreamingScorer` per cluster store: N tenants
meant N feature tables, N evidence tables, and N device passes per
verdict round. This module packs every tenant onto one scorer:

* **Slot-space namespacing.** The node and incident slot spaces are
  carved into contiguous per-tenant REGIONS (each sized by the tenant's
  own bucket ladder rungs — the "static incident-bucket ladder": the
  packed incident dim is a sum of `settings.incident_bucket_sizes`
  rungs, so it stays static while every tenant stays inside its rung).
  Host bookkeeping keys node ids as ``tenant::local_id``; evidence slots
  carry GLOBAL node rows, so the stock fused tick
  (:func:`~..rca.streaming._tick` — donated resident state, delta
  scatters, dense evidence fold) runs UNCHANGED over the pack and one
  jitted pass scores every tenant's live incidents at once. The
  optionally sharded resident state (``settings.serve_graph_shards``)
  composes for free: the packed shapes divide over the graph axis
  exactly like single-tenant shapes, and the per-shard delta router is
  region-agnostic (rows route by owner shard, not by tenant).

* **Per-tenant journal cursors.** ``sync()`` drains EVERY tenant store's
  change journal into the shared pending-delta set — many webhook
  writers, one coalesced tick stream. Each tenant's incident region
  carries ONE rung of arrival headroom (incident rows are the cheap
  axis), so bursts land in free rows; a region that still overflows
  triggers the INCREMENTAL repack (``_repack``): only the overflowing
  tenant re-tensorizes, the kept regions' host mirrors move by a row
  shift (counted in ``rebuilds``/``partial_repacks``) — one tenant's
  growth costs one tenant's tensorize, never N.

* **Per-tenant quarantine.** A poisoned delta (non-finite staged rows)
  or a truncated journal quarantines ONLY the offending tenant: its
  rows drop out of the staged delta, its journal stops draining, and the
  next sync HEALS it — a region-scoped store-derived re-mirror staged as
  in-place deltas through the shared tick (``tenant_rebuilds``). The
  other tenants' resident rows, in-flight ticks, and verdicts never
  stall — the failure-isolation contract the single-store
  :class:`~.streaming.NonFiniteDelta` path cannot offer.

:class:`SurgeServer` is the process-wide front-end the workflow workers
attach to: each per-tenant :class:`~..workflow.worker.IncidentWorker`
registers its builder's store at construction, and the shared scorer
builds lazily at first serve. Together with ``absorb()`` (tick_async at
webhook ingest) and ``serve(newest=True)`` (deferred newest-tick fetch)
this is the ROADMAP item-2 refactor: webhook bursts feed the bounded
async queue directly, and concurrent incidents from many tenants cost
ONE device pass, not one pass per incident.
"""
from __future__ import annotations

import bisect
import collections
import json
import os
import threading
import time
from dataclasses import dataclass
from typing import Iterable, Mapping

import numpy as np

import jax.numpy as jnp

from ..config import Settings, get_settings
from ..graph.schema import EntityKind, RelationKind
from ..graph.snapshot import GraphSnapshot, build_snapshot
from ..graph.store import EvidenceGraphStore
from ..observability import get_logger
from ..observability import metrics as obs_metrics
from ..observability import scope as obs_scope
from ..utils.padding import bucket_for
from .streaming import _DELTA_BUCKETS, _ROW_BUCKETS, StreamingScorer
from .tpu_backend import _PAIR_WIDTH_BUCKETS, _WIDTH_BUCKETS

log = get_logger("surge")

NS_SEP = "::"


def tenant_node_id(tenant: str, node_id: str) -> str:
    """The pack's slot-space id for a tenant-local store node id."""
    return f"{tenant}{NS_SEP}{node_id}"


def split_tenant_id(nsid: str) -> tuple[str, str]:
    """(tenant, local_id) of a namespaced slot-space id."""
    tenant, sep, local = nsid.partition(NS_SEP)
    if not sep:
        return "default", nsid
    return tenant, local


@dataclass
class TenantRegion:
    """One tenant's contiguous slice of the packed slot spaces."""
    name: str
    store: EvidenceGraphStore
    node_base: int = 0
    pn: int = 0
    inc_base: int = 0
    pi: int = 0
    synced_seq: int = 0
    quarantined: bool = False
    heal_pending: bool = False
    quarantines: int = 0
    rebuilds: int = 0


class MultiTenantScorer(StreamingScorer):
    """StreamingScorer over a PACK of tenant stores (see module doc).

    The base class's mutation API, delta staging, pipelined executor
    (tick_async/absorb/rescore_newest), warm machinery and sharded
    dispatch all operate on the packed state unchanged; this subclass
    only re-derives initialisation per tenant region, routes allocation
    and store lookups through the id's tenant, drains every tenant's
    journal in ``sync()``, and adds the quarantine/heal ladder.
    """

    def __init__(self, stores: "Mapping[str, EvidenceGraphStore] | Iterable[tuple[str, EvidenceGraphStore]]",
                 settings: Settings | None = None,
                 mesh=None, now_s: float | None = None) -> None:
        items = dict(stores)
        if not items:
            raise ValueError("MultiTenantScorer needs at least one tenant")
        self._tenant_stores: dict[str, EvidenceGraphStore] = items
        self.tenant_rebuilds = 0
        self.partial_repacks = 0
        self.quarantines = 0
        super().__init__(store=None, settings=settings, mesh=mesh,
                         now_s=now_s)

    # -- identity / region seams ------------------------------------------

    def _tenant_count(self) -> int:
        return len(self._tenant_stores)

    def serving_node_id(self, node_id: str, tenant: str = "default") -> str:
        return tenant_node_id(tenant, node_id)

    def _canon_incident_id(self, incident_node_id: str) -> str:
        # journal-driven ids arrive canonical and namespaced already
        return incident_node_id

    def _region_of_node_row(self, row: int) -> TenantRegion:
        i = bisect.bisect_right(self._node_bases, row) - 1
        return self._regions_order[i]

    def _region_of_inc_row(self, r: int) -> TenantRegion:
        i = bisect.bisect_right(self._inc_bases, r) - 1
        return self._regions_order[i]

    def _node_row_available(self, node_id: str) -> bool:
        return bool(self._free_node_rows.get(split_tenant_id(node_id)[0]))

    def _take_node_row(self, node_id: str) -> int:
        return self._free_node_rows[split_tenant_id(node_id)[0]].pop()

    def _put_node_row(self, row: int) -> None:
        self._free_node_rows[self._region_of_node_row(row).name].append(row)

    def _inc_row_available(self, node_id: str) -> bool:
        return bool(self._free_inc_rows.get(split_tenant_id(node_id)[0]))

    def _take_inc_row(self, node_id: str) -> int:
        return self._free_inc_rows[split_tenant_id(node_id)[0]].pop()

    def _put_inc_row(self, row: int) -> None:
        self._free_inc_rows[self._region_of_inc_row(row).name].append(row)

    def _store_node(self, node_id: str):
        tenant, local = split_tenant_id(node_id)
        store = self._tenant_stores.get(tenant)
        return None if store is None else store._nodes.get(local)

    # -- (re)initialisation: the pack -------------------------------------

    def _alloc_pack(self, pn: int, pi: int, dim: int,
                    node_kind_dtype, inc_dtype) -> None:
        """Fresh packed snapshot mirror + empty global host structures at
        total shape (pn, pi). Edge arrays stay empty — they feed only the
        base single-store init path; the pack mirrors per region."""
        self.snapshot = GraphSnapshot(
            node_ids=(), incident_ids=(),
            num_nodes=0, num_edges=0, num_incidents=0,
            node_kind=np.zeros(pn, node_kind_dtype),
            features=np.zeros((pn, dim), np.float32),
            node_mask=np.zeros(pn, np.float32),
            edge_src=np.zeros(0, np.int32), edge_dst=np.zeros(0, np.int32),
            edge_rel=np.zeros(0, np.int32),
            edge_mask=np.zeros(0, np.float32),
            incident_nodes=np.zeros(pi, inc_dtype),
            incident_mask=np.zeros(pi, np.float32),
        )
        self._node_ids = [None] * pn
        self._id_to_idx = {}
        self._free_node_rows: dict[str, list[int]] = {}
        self._inc_row_of = {}
        self._row_inc = [None] * pi
        self._free_inc_rows: dict[str, list[int]] = {}
        self._pod_node = {}
        self._sched_pods = {}
        self._row_nodes = [[] for _ in range(pi)]
        self._row_pairs = [[] for _ in range(pi)]
        self._pair_map = [{} for _ in range(pi)]
        self._ev_rows_of_node = {}

    def _finalize_pack(self) -> None:
        """Derive widths + rebuild the resident device state from the
        freshly packed host mirror (shared tail of the full init and the
        incremental repack)."""
        pi = self.snapshot.padded_incidents
        self._node_bases = [r.node_base for r in self._regions_order]
        self._inc_bases = [r.inc_base for r in self._regions_order]
        self.width, self.pair_width = self._rebuild_widths()
        self._features_dev = jnp.asarray(self.snapshot.features)
        ev_idx, ev_cnt, ev_pair = self._materialize_rows(range(pi))
        self._ev_idx_dev = jnp.asarray(ev_idx)
        self._ev_cnt_dev = jnp.asarray(ev_cnt)
        self._pair_dev = jnp.asarray(ev_pair)
        self._chain0 = jnp.zeros((pi,), jnp.float32)
        self._apply_sharding()
        # graft-intake: same columnar/dict staging switch as the base
        # scorer's _init_from_store — the pack rides the identical drain
        if getattr(self.settings, "ingest_columnar", False):
            from .streaming import FeatureStage
            self._pending_feat = FeatureStage(
                self.snapshot.features.shape[1])
        else:
            self._pending_feat = {}
        self._dirty_rows = set()
        self._synced_seq = 0   # unused by the pack (per-region cursors)

    def _init_from_store(self) -> None:
        """Tensorize EVERY tenant store and pack the per-tenant snapshots
        into one resident state with contiguous regions. Per-tenant
        journal cursors are captured BEFORE tensorizing (the base
        scorer's replay-idempotence argument, per store)."""
        self._drop_stale_inflight()
        packs: list[tuple[TenantRegion, GraphSnapshot]] = []
        self.regions: dict[str, TenantRegion] = {}
        self._regions_order: list[TenantRegion] = []
        node_base = inc_base = 0
        for name, store in self._tenant_stores.items():
            seq = store.journal_seq
            snap = build_snapshot(store, self.settings, slack=1 / 3,
                                  now_s=self.now_s)
            reg = TenantRegion(name=name, store=store,
                               node_base=node_base, pn=snap.padded_nodes,
                               inc_base=inc_base,
                               pi=self._region_pi(snap.padded_incidents),
                               synced_seq=seq)
            self.regions[name] = reg
            self._regions_order.append(reg)
            node_base += reg.pn
            inc_base += reg.pi
            packs.append((reg, snap))
        first = packs[0][1]
        self._alloc_pack(node_base, inc_base, first.features.shape[1],
                         first.node_kind.dtype, first.incident_nodes.dtype)
        for reg, snap in packs:
            self._mirror_region(reg, snap)
        self._finalize_pack()

    def _rebuild(self) -> None:
        """Pack rebuild on bucket overflow. Unlike the base scorer's
        whole-store re-tensorize, the pack repacks INCREMENTALLY: only
        tenants whose stores outgrew their regions (or whose free rows
        ran dry) pay the per-tenant tensorize; every other region's host
        mirror MOVES — row-shifted numpy/dict copies — so one tenant's
        growth rebuild costs one tenant's tensorize, not N (the
        "one tenant's rebuild never stalls the others" contract, for the
        overflow case the static regions cannot absorb in place)."""
        self.rebuilds += 1
        if getattr(self, "_regions_order", None):
            self._repack()
        else:
            self._init_from_store()
        self._rearm_warm_growth()

    def _repack(self) -> None:
        from ..graph.schema import EntityKind as _EK
        self._drop_stale_inflight()
        old_snapshot = self.snapshot
        old = {
            "node_ids": self._node_ids, "row_inc": self._row_inc,
            "free_nodes": self._free_node_rows,
            "free_incs": self._free_inc_rows,
            "pod_node": self._pod_node, "sched_pods": self._sched_pods,
            "row_nodes": self._row_nodes, "row_pairs": self._row_pairs,
            "pair_map": self._pair_map, "ev_rows": self._ev_rows_of_node,
        }
        old_bases = {r.name: (r.node_base, r.inc_base)
                     for r in self._regions_order}
        incident_kind = int(_EK.INCIDENT)
        plans: list[tuple[TenantRegion, GraphSnapshot | None]] = []
        node_base = inc_base = 0
        retensorized = []
        for reg in self._regions_order:
            store = reg.store
            live_inc = sum(1 for n in store._nodes.values()
                           if int(n.kind) == incident_kind)
            need_pn = bucket_for(
                max(int(np.ceil(len(store._nodes) * 4 / 3)), 1),
                self.settings.node_bucket_sizes)
            need_pi = bucket_for(max(int(np.ceil(live_inc * 4 / 3)), 1),
                                 self.settings.incident_bucket_sizes)
            keep = (need_pn <= reg.pn and need_pi <= reg.pi
                    and bool(old["free_nodes"].get(reg.name))
                    and bool(old["free_incs"].get(reg.name))
                    and not reg.quarantined and not reg.heal_pending)
            if keep:
                snap = None               # mirror moves; sizes unchanged
            else:
                reg.synced_seq = store.journal_seq
                snap = build_snapshot(store, self.settings, slack=1 / 3,
                                      now_s=self.now_s)
                reg.pn = max(snap.padded_nodes, need_pn)
                reg.pi = self._region_pi(
                    max(snap.padded_incidents, need_pi))
                reg.quarantined = False
                reg.heal_pending = False
                retensorized.append(reg.name)
            reg.node_base, reg.inc_base = node_base, inc_base
            node_base += reg.pn
            inc_base += reg.pi
            plans.append((reg, snap))
        self._alloc_pack(node_base, inc_base,
                         old_snapshot.features.shape[1],
                         old_snapshot.node_kind.dtype,
                         old_snapshot.incident_nodes.dtype)
        for reg, snap in plans:
            if snap is None:
                onb, oib = old_bases[reg.name]
                self._shift_region(old, old_snapshot, reg, onb, oib)
            else:
                self._mirror_region(reg, snap)
        self._finalize_pack()
        self.partial_repacks += 1
        log.warning("pack_repacked", retensorized=retensorized,
                    kept=[r.name for r in self._regions_order
                          if r.name not in retensorized])

    def _shift_region(self, old: dict, osnap: GraphSnapshot,
                      reg: TenantRegion, onb: int, oib: int) -> None:
        """Move one kept region's host mirror from its old bases to its
        new ones: numpy slice copies for the packed arrays, constant row
        shifts for every bookkeeping structure. Evidence and scheduling
        references never cross tenants, so the shift is closed over the
        region by construction. Pending feature values already live in
        the snapshot mirror (update_nodes writes both), so the post-pack
        device re-upload subsumes them."""
        nb, ib = reg.node_base, reg.inc_base
        dn, di = nb - onb, ib - oib
        self.snapshot.features[nb:nb + reg.pn] = \
            osnap.features[onb:onb + reg.pn]
        self.snapshot.node_kind[nb:nb + reg.pn] = \
            osnap.node_kind[onb:onb + reg.pn]
        self.snapshot.node_mask[nb:nb + reg.pn] = \
            osnap.node_mask[onb:onb + reg.pn]
        self.snapshot.incident_nodes[ib:ib + reg.pi] = \
            osnap.incident_nodes[oib:oib + reg.pi] + dn
        self.snapshot.incident_mask[ib:ib + reg.pi] = \
            osnap.incident_mask[oib:oib + reg.pi]
        for i in range(reg.pn):
            nid = old["node_ids"][onb + i]
            self._node_ids[nb + i] = nid
            if nid is not None:
                self._id_to_idx[nid] = nb + i
        self._free_node_rows[reg.name] = [
            r + dn for r in old["free_nodes"][reg.name]]
        for r in range(reg.pi):
            iid = old["row_inc"][oib + r]
            self._row_inc[ib + r] = iid
            if iid is not None:
                self._inc_row_of[iid] = ib + r
            self._row_nodes[ib + r] = [n + dn
                                       for n in old["row_nodes"][oib + r]]
            self._row_pairs[ib + r] = list(old["row_pairs"][oib + r])
            self._pair_map[ib + r] = dict(old["pair_map"][oib + r])
        self._free_inc_rows[reg.name] = [
            r + di for r in old["free_incs"][reg.name]]
        for pod, node in old["pod_node"].items():
            if onb <= pod < onb + reg.pn:
                self._pod_node[pod + dn] = node + dn
        for node, pods in old["sched_pods"].items():
            if onb <= node < onb + reg.pn:
                self._sched_pods[node + dn] = {p + dn for p in pods}
        for node, rows in old["ev_rows"].items():
            if onb <= node < onb + reg.pn:
                self._ev_rows_of_node[node + dn] = {r + di for r in rows}

    def _region_pi(self, padded: int) -> int:
        """A tenant's incident region = its store-derived bucket PLUS one
        rung of arrival headroom. Incident rows are the cheap axis of the
        resident state (int slot tables, no [Pn, DIM] features), and the
        multi-tenant serving regime is exactly the one where a tenant's
        concurrent incidents burst past its cold bucket — one spare rung
        absorbs the burst in place instead of paying a pack repack that
        pauses every tenant's verdicts for a round."""
        return bucket_for(padded + 1, self.settings.incident_bucket_sizes)

    def _mirror_region(self, reg: TenantRegion, snap: GraphSnapshot) -> None:
        """Install one tenant's snapshot into its region: packed array
        slices, namespaced id maps, region free lists, and the evidence /
        scheduled-on host bookkeeping at GLOBAL rows. Used by the initial
        pack (snap shapes == region shapes) and by a heal (snap may have
        shrunk — the region's tail rows become free)."""
        t, nb, ib = reg.name, reg.node_base, reg.inc_base
        spn, spi = snap.padded_nodes, snap.padded_incidents
        self.snapshot.features[nb:nb + spn] = snap.features
        self.snapshot.features[nb + spn:nb + reg.pn] = 0.0
        self.snapshot.node_kind[nb:nb + spn] = snap.node_kind
        self.snapshot.node_kind[nb + spn:nb + reg.pn] = 0
        self.snapshot.node_mask[nb:nb + spn] = snap.node_mask
        self.snapshot.node_mask[nb + spn:nb + reg.pn] = 0.0
        self.snapshot.incident_nodes[ib:ib + spi] = snap.incident_nodes + nb
        self.snapshot.incident_nodes[ib + spi:ib + reg.pi] = 0
        self.snapshot.incident_mask[ib:ib + spi] = snap.incident_mask
        self.snapshot.incident_mask[ib + spi:ib + reg.pi] = 0.0

        for i, nid in enumerate(snap.node_ids):
            gid = tenant_node_id(t, nid)
            self._node_ids[nb + i] = gid
            self._id_to_idx[gid] = nb + i
        self._free_node_rows[t] = list(
            range(nb + reg.pn - 1, nb + snap.num_nodes - 1, -1))
        for r, iid in enumerate(snap.incident_ids):
            gid = tenant_node_id(t, iid)
            self._inc_row_of[gid] = ib + r
            self._row_inc[ib + r] = gid
        self._free_inc_rows[t] = list(
            range(ib + reg.pi - 1, ib + snap.num_incidents - 1, -1))

        live = snap.edge_mask > 0
        sched = live & (snap.edge_rel == int(RelationKind.SCHEDULED_ON))
        for pos in np.nonzero(sched)[0]:
            s, d = int(snap.edge_src[pos]), int(snap.edge_dst[pos])
            pod, node = ((s, d) if snap.node_kind[s] == int(EntityKind.POD)
                         else (d, s))
            self._set_pod_node(nb + pod, nb + node)
        is_ev = live & ((snap.edge_rel == int(RelationKind.AFFECTS))
                        | (snap.edge_rel == int(RelationKind.CORRELATES_WITH)))
        inc_row = np.full(spn, -1, dtype=np.int64)
        real = snap.incident_mask > 0
        inc_row[snap.incident_nodes[real]] = np.arange(int(real.sum()))
        for pos in np.nonzero(is_ev)[0]:
            r = int(inc_row[snap.edge_src[pos]])
            if r < 0:
                continue   # undirected duplicate (dst is the incident)
            self._append_evidence_host(ib + r, nb + int(snap.edge_dst[pos]))

    # -- multi-journal sync + quarantine/heal ------------------------------

    def _ns_record(self, tenant: str, rec: tuple) -> tuple:
        op = rec[1]
        if op in ("edge+", "edge-"):
            return (rec[0], op, tenant_node_id(tenant, rec[2]),
                    tenant_node_id(tenant, rec[3]), *rec[4:])
        return (rec[0], op, tenant_node_id(tenant, rec[2]), *rec[3:])

    def sync(self) -> dict:
        """Drain EVERY tenant's store journal into the packed resident
        state — one coalesced delta stream for N webhook writers.
        Quarantined tenants heal first (region re-mirror) and skip the
        drain; a truncated journal quarantines + heals only its tenant;
        a region overflow mid-batch escalates to a full repack, which
        re-captures every cursor (remaining records are reflected)."""
        self.syncs += 1
        totals = {"applied": 0, "structural": 0, "feature": 0,
                  "rebuilt": False, "healed": 0}
        for reg in self._regions_order:
            if reg.heal_pending:
                rb0 = self.rebuilds
                self._heal(reg)
                totals["healed"] += 1
                if self.rebuilds != rb0:   # heal escalated to a repack
                    totals["rebuilt"] = True
                    return totals
        for reg in self._regions_order:
            if reg.quarantined:
                continue
            recs, seq, truncated = reg.store.journal_since(reg.synced_seq)
            if truncated:
                self.quarantine(reg.name, "journal_truncated")
                rb0 = self.rebuilds
                self._heal(reg)
                totals["healed"] += 1
                if self.rebuilds != rb0:
                    totals["rebuilt"] = True
                    return totals
                continue
            if recs:
                rb0 = self.rebuilds
                res = self._apply_records(
                    [self._ns_record(reg.name, r) for r in recs])
                totals["applied"] += res["applied"]
                totals["structural"] += res.get("structural", 0)
                totals["feature"] += res.get("feature", 0)
                if self.rebuilds != rb0:
                    totals["rebuilt"] = True
                    return totals
            reg.synced_seq = max(seq, reg.synced_seq)
        self._note_queue_depths()
        return totals

    def _journal_backlog(self) -> int:
        """graft-storm: the pack's undrained backlog is the SUM over
        tenant journals (quarantined regions excluded — their journal
        deliberately stops draining until the heal)."""
        return sum(
            max(int(reg.store.journal_seq) - int(reg.synced_seq), 0)
            for reg in self._regions_order if not reg.quarantined)

    def _note_queue_depths(self) -> None:
        counts = {reg.name: 0 for reg in self._regions_order}
        for row in self._pending_feat:
            counts[self._region_of_node_row(row).name] += 1
        for r in self._dirty_rows:
            counts[self._region_of_inc_row(r).name] += 1
        for name, c in counts.items():
            obs_metrics.SERVE_TENANT_QUEUE_DEPTH.set(float(c), tenant=name)

    def quarantine(self, tenant: str, reason: str) -> None:
        """Take one tenant off the shared tick: its staged deltas drop,
        its journal stops draining, and the next sync() heals its region
        from store truth. Every OTHER tenant keeps ticking — this is the
        failure-isolation contract of the pack."""
        reg = self.regions[tenant]
        if not reg.quarantined:
            reg.quarantined = True
            reg.heal_pending = True
            reg.quarantines += 1
            self.quarantines += 1
            obs_metrics.SERVE_TENANT_QUARANTINES.inc(tenant=tenant)
            obs_scope.FLIGHT_RECORDER.note_event(
                "tenant_quarantined", tenant=tenant, reason=reason)
            log.warning("tenant_quarantined", tenant=tenant, reason=reason)
        nb, ne = reg.node_base, reg.node_base + reg.pn
        pf = self._pending_feat
        if hasattr(pf, "discard_range"):
            # graft-intake columnar stage: one vectorized compaction,
            # surviving rows keep their staging order
            pf.discard_range(nb, ne)
        else:
            self._pending_feat = {k: v for k, v in pf.items()
                                  if not nb <= k < ne}
        ib, ie = reg.inc_base, reg.inc_base + reg.pi
        self._dirty_rows = {r for r in self._dirty_rows if not ib <= r < ie}

    def _heal(self, reg: TenantRegion) -> None:
        """Region-scoped store-derived re-mirror — the per-tenant rebuild.
        Re-tensorizes ONLY this tenant's store and stages its whole
        region as in-place deltas through the shared tick: the other
        tenants' resident rows and in-flight results are untouched.
        Escalates to a full repack when the fresh store outgrew the
        region, or when the region itself exceeds the delta ladder a
        staged re-mirror must ride."""
        seq = reg.store.journal_seq
        snap = build_snapshot(reg.store, self.settings, slack=1 / 3,
                              now_s=self.now_s)
        if (snap.padded_nodes > reg.pn or snap.padded_incidents > reg.pi
                or reg.pn > _DELTA_BUCKETS[-1]):
            log.warning("tenant_region_outgrown", tenant=reg.name,
                        region_pn=reg.pn, need_pn=snap.padded_nodes,
                        region_pi=reg.pi, need_pi=snap.padded_incidents)
            self._rebuild()
            return
        self._clear_region(reg)
        self._mirror_region(reg, snap)
        # stage the WHOLE region: every node row ships as a feature delta
        # (zeros for dead rows — stale resident rows must fold 0) and
        # every incident row re-ships its slot tables
        for row in range(reg.node_base, reg.node_base + reg.pn):
            self._pending_feat[row] = np.array(self.snapshot.features[row],
                                               copy=True)
        self._dirty_rows.update(range(reg.inc_base, reg.inc_base + reg.pi))
        rb0 = self.rebuilds
        w, pw = self._rebuild_widths()
        if w > self.width:
            self._grow(self._grow_width)
        if self.rebuilds == rb0 and pw > self.pair_width:
            self._grow(self._grow_pair_width)
        if self.rebuilds != rb0:
            return   # growth ladder exhausted → full repack superseded us
        reg.synced_seq = seq
        reg.quarantined = False
        reg.heal_pending = False
        reg.rebuilds += 1
        self.tenant_rebuilds += 1
        obs_metrics.SERVE_TENANT_REBUILDS.inc(tenant=reg.name)
        obs_scope.FLIGHT_RECORDER.note_event("tenant_healed",
                                             tenant=reg.name)
        log.info("tenant_healed", tenant=reg.name,
                 staged_rows=reg.pn, dirty_rows=reg.pi)

    def _clear_region(self, reg: TenantRegion) -> None:
        """Forget one region's host bookkeeping (its packed array slices
        are overwritten by the following _mirror_region). Evidence and
        scheduled-on references never cross tenants, so the sweep is
        region-local by construction."""
        nb, ne = reg.node_base, reg.node_base + reg.pn
        ib, ie = reg.inc_base, reg.inc_base + reg.pi
        for row in range(nb, ne):
            nid = self._node_ids[row]
            if nid is not None:
                self._id_to_idx.pop(nid, None)
                self._node_ids[row] = None
            self._pod_node.pop(row, None)
            self._sched_pods.pop(row, None)
            self._ev_rows_of_node.pop(row, None)
        for r in range(ib, ie):
            iid = self._row_inc[r]
            if iid is not None:
                self._inc_row_of.pop(iid, None)
                self._row_inc[r] = None
            self._row_nodes[r] = []
            self._row_pairs[r] = []
            self._pair_map[r] = {}
        self._free_node_rows[reg.name] = []
        self._free_inc_rows[reg.name] = []

    # -- per-tenant poison screening ---------------------------------------

    def _screen_delta(self, f_idx: np.ndarray, f_rows: np.ndarray,
                      span) -> tuple[np.ndarray, np.ndarray]:
        """Finite guard, tenant-scoped: non-finite staged rows are dropped
        from THIS delta (index → out-of-range sentinel) and their tenants
        quarantined for a store-derived heal at the next sync — the tick
        proceeds for every other tenant instead of raising
        NonFiniteDelta across the whole pack."""
        if not self.finite_delta_guard:
            return f_idx, f_rows
        finite = np.isfinite(f_rows).all(axis=-1)
        if finite.all():
            return f_idx, f_rows
        f_idx = np.array(f_idx, copy=True)
        f_rows = np.array(f_rows, copy=True)
        pn = self.snapshot.padded_nodes
        poisoned: set[str] = set()
        if f_idx.ndim == 2:   # graph-sharded: [G, pk] shard-LOCAL indices
            nps = pn // f_idx.shape[0]
            for gi, j in np.argwhere(~finite):
                local = int(f_idx[gi, j])
                if local < nps:
                    poisoned.add(self._region_of_node_row(
                        gi * nps + local).name)
                f_idx[gi, j] = nps
                f_rows[gi, j] = 0.0
        else:
            for (j,) in np.argwhere(~finite):
                row = int(f_idx[j])
                if row < pn:
                    poisoned.add(self._region_of_node_row(row).name)
                f_idx[j] = pn
                f_rows[j] = 0.0
        for t in sorted(poisoned):
            self.quarantine(t, "nonfinite_delta")
        if span is not None and poisoned:
            span.flag("nonfinite_delta_quarantined")
        return f_idx, f_rows

    # -- warm-growth shapes -------------------------------------------------

    def _growth_warm_buckets(self) -> tuple[tuple[int, ...],
                                            tuple[int, ...]]:
        """A mid-batch incremental repack leaves the kept tenants'
        un-drained journal records for the next sync, so the first
        post-repack ticks carry a MULTI-tenant delta batch: warm the
        first two rungs of both delta ladders, not just the smallest."""
        return (_DELTA_BUCKETS[:2], _ROW_BUCKETS[:2])

    def _growth_shape_combos(self) -> list[tuple[int, int, int, int, int]]:
        """Pack variant of the base derivation. Warmable repack targets:
        the current shape (width growths keep it), the shape a full
        store-derived repack would land on NOW, and — the common case —
        ONE region overflowing to its next rung while the others keep
        their size (the incremental `_repack`). Regions share rungs, so
        the per-region next-rung shapes dedupe to a handful."""
        with self.serve_lock:
            pn = self.snapshot.padded_nodes
            pi = self.snapshot.padded_incidents
            dim = self.snapshot.features.shape[1]
            inc_counts = {reg.name: 0 for reg in self._regions_order}
            for r in self._inc_row_of.values():
                inc_counts[self._region_of_inc_row(r).name] += 1
            pn_now = sum(
                bucket_for(max(int(np.ceil(
                    len(reg.store._nodes) * 4 / 3)), 1),
                    self.settings.node_bucket_sizes)
                for reg in self._regions_order)
            pi_now = sum(
                bucket_for(max(int(np.ceil(
                    inc_counts[reg.name] * 4 / 3)), 1),
                    self.settings.incident_bucket_sizes)
                for reg in self._regions_order)
            shapes = {(pn, pi), (pn_now, pi_now)}
            for reg in self._regions_order:
                next_pn = bucket_for(reg.pn + 1,
                                     self.settings.node_bucket_sizes)
                next_pi = bucket_for(reg.pi + 1,
                                     self.settings.incident_bucket_sizes)
                shapes.add((pn - reg.pn + next_pn, pi))
                shapes.add((pn, pi - reg.pi + next_pi))
            rw, rpw = self._rebuild_widths()
            next_pw = next((w for w in _PAIR_WIDTH_BUCKETS
                            if w > self.pair_width), self.pair_width)
            widths = {self.width, rw,
                      bucket_for(self.width + 1, _WIDTH_BUCKETS)}
            pws = {self.pair_width, rpw, next_pw}
        return [(cpn, cpi, w, pw, dim)
                for (cpn, cpi) in shapes for w in widths for pw in pws]

    # -- per-tenant unpacking at the fetch boundary -------------------------

    def tenant_rows(self, raw: dict) -> dict[str, dict]:
        """Unpack one batched raw verdict dict into per-tenant dicts with
        LOCAL (namespace-stripped) incident ids — exactly the shape the
        per-tenant backends' ``results(raw=...)`` expect. This is the
        "per-tenant row slices unpacked at fetch" boundary: the device
        pass was one; the slicing is host numpy."""
        ids = raw["incident_ids"]
        n = len(ids)
        per: dict[str, tuple[list[str], list[int]]] = {}
        for i, nsid in enumerate(ids):
            t, local = split_tenant_id(nsid)
            per.setdefault(t, ([], []))
            per[t][0].append(local)
            per[t][1].append(i)
        out: dict[str, dict] = {}
        for t, (locals_, idxs) in per.items():
            d = {"incident_ids": tuple(locals_)}
            for k, v in raw.items():
                if isinstance(v, np.ndarray) and v.shape[:1] == (n,):
                    d[k] = v[idxs]
            out[t] = d
        return out

    # -- graft-swell: live tenant membership (migration seams) --------------

    def add_tenant(self, name: str, store: EvidenceGraphStore) -> None:
        """Adopt one NEW tenant into the running pack at a generation
        boundary: a fresh zero-sized region is appended and the
        incremental ``_repack`` tensorizes ONLY the newcomer (a pn=0
        region can never satisfy the keep condition), while every kept
        region's host mirror moves by a row shift. This is the
        destination half of a tenant migration — the journal-cursor
        handoff above it (SurgeServer.migrate) owns exactly-once."""
        with self.serve_lock:
            if name in self._tenant_stores:
                raise ValueError(f"tenant {name!r} already in the pack")
            self._tenant_stores[name] = store
            reg = TenantRegion(name=name, store=store)
            self.regions[name] = reg
            self._regions_order.append(reg)
            self._repack()
        self._rearm_warm_growth()
        obs_scope.FLIGHT_RECORDER.note_event("tenant_adopted", tenant=name)
        log.info("tenant_adopted", tenant=name,
                 tenants=len(self._tenant_stores))

    def remove_tenant(self, name: str) -> EvidenceGraphStore:
        """Release one tenant from the running pack (the source half of
        a migration): its region drops out of the packed slot spaces and
        the incremental ``_repack`` row-shifts the survivors — no
        surviving tenant pays a tensorize. The pack must keep at least
        one tenant (an empty MultiTenantScorer cannot exist; the owning
        SurgeServer drops the whole pack instead). Returns the released
        tenant's store for the destination pack to adopt."""
        with self.serve_lock:
            if name not in self._tenant_stores:
                raise KeyError(f"tenant {name!r} not in the pack")
            if len(self._tenant_stores) == 1:
                raise ValueError(
                    "a pack cannot drop its last tenant — the owner "
                    "retires the whole pack instead")
            store = self._tenant_stores.pop(name)
            reg = self.regions.pop(name)
            self._regions_order.remove(reg)
            # the departing region's staged deltas must not survive into
            # the repacked slot spaces (quarantine's delta-scrub rule)
            nb, ne = reg.node_base, reg.node_base + reg.pn
            pf = self._pending_feat
            if hasattr(pf, "discard_range"):
                pf.discard_range(nb, ne)
            else:
                self._pending_feat = {k: v for k, v in pf.items()
                                      if not nb <= k < ne}
            ib, ie = reg.inc_base, reg.inc_base + reg.pi
            self._dirty_rows = {r for r in self._dirty_rows
                                if not ib <= r < ie}
            self._repack()
        self._rearm_warm_growth()
        obs_scope.FLIGHT_RECORDER.note_event("tenant_released", tenant=name)
        log.info("tenant_released", tenant=name,
                 tenants=len(self._tenant_stores))
        return store


def swap_tenants_atomically(targets, params, source: str = "") -> int:
    """graft-evolve: flip EVERY tenant's resident GNN scorer to one new
    params generation atomically. The rules pack (MultiTenantScorer)
    carries no learned params — multi-tenant GNN serving keeps per-tenant
    resident scorers riding the same async protocol (ROADMAP item 2), so
    "tenants swap atomically together" means: acquire every tenant
    scorer's ``serve_lock`` FIRST (in the caller's stable registration
    order — every swapper must use this helper, which is what makes the
    ordered acquisition deadlock-free), then install the same generation
    through each scorer's locked seam. No tick on any tenant can observe
    a mix: each tenant's in-flight ticks complete on the old generation,
    and every dispatch that starts after this returns serves the new one.
    Shield-wrapped targets WAL-journal the swap (exact leaves) before it
    applies, per the crash-consistency invariant. Returns the shared new
    generation (1 + the max across tenants, so replay ordering stays
    monotonic for every journal)."""
    import jax
    from contextlib import ExitStack
    targets = list(targets)
    if not targets:
        raise ValueError("swap_tenants_atomically needs >= 1 scorer")
    with ExitStack() as stack:
        for t in targets:
            stack.enter_context(t.serve_lock)
        gen = 1 + max(int(getattr(t, "params_generation", 0))
                      for t in targets)
        leaves = None
        for t in targets:
            journal = getattr(t, "journal", None)   # ShieldedScorer seam
            scorer = getattr(t, "scorer", t)
            if journal is not None:
                if leaves is None:
                    leaves = [np.asarray(x)
                              for x in jax.tree_util.tree_leaves(params)]
                seq = int(scorer._synced_seq)
                journal.append((), seq, seq, kind="params_swap",
                               force_sync=True, generation=gen,
                               leaves=leaves, source=source)
            # graft-audit: allow[wal-order] unshielded tenants have no journal to write; shielded tenants journaled in the branch above before this locked install
            scorer._swap_params_locked(params, gen, source=source)
    obs_metrics.LEARN_SWAPS.inc()
    obs_scope.FLIGHT_RECORDER.note_event(
        "params_swap_atomic", generation=gen, tenants=len(targets),
        source=source)
    return gen


class _FleetJournal:
    """Append-only WAL for fleet PLACEMENT mutations (graft-swell).

    The shield's record journal cannot own tenant migration — the shield
    is unsupported on packs (``ShieldedScorer`` needs ``scorer.store``;
    a MultiTenantScorer has none) — so the fleet keeps its own tiny WAL
    with the same discipline: journal-before-mutate, fsync on append,
    roll-FORWARD replay. Records are plain dicts; with ``path=None`` the
    journal is in-memory only (single-process tests, the default
    single-pack deployment where placement is trivially recoverable)."""

    def __init__(self, path: "str | None" = None) -> None:
        self.path = path or None
        self._lock = threading.Lock()
        self._records: list[dict] = []
        if self.path and os.path.exists(self.path):
            with open(self.path) as f:
                self._records = [json.loads(line)
                                 for line in f if line.strip()]

    def append(self, rec: dict) -> None:
        line = json.dumps(rec, sort_keys=True)
        with self._lock:
            self._records.append(dict(rec))
            if self.path:
                with open(self.path, "a") as f:
                    f.write(line + "\n")
                    f.flush()
                    os.fsync(f.fileno())

    def replay(self) -> list[dict]:
        with self._lock:
            return [dict(r) for r in self._records]


class SurgeServer:
    """Process-wide multi-tenant serving front-end — a FLEET of packs.

    Per-tenant workflow workers register their builder's store at
    construction; each tenant is bin-packed onto one
    :class:`MultiTenantScorer` pack (its own serving mesh), placed by
    per-tenant load estimate (admitted-rows/s EWMA over store-journal
    cursor deltas). Packs build lazily on the first ``scorer(tenant)``
    call; registering a NEW tenant after a build marks only its pack
    stale, and workers detect staleness cheaply via ``fresh()``.

    With ``settings.swell_max_packs == 1`` (the default) every tenant
    lands on pack 0 and the behavior is exactly the single-pack PR-9
    server. With N packs, ``migrate()`` moves a tenant between packs
    live: journal-cursor handoff through the fleet WAL
    (journal-before-mutate, exactly-once — crash mid-migration recovers
    to exactly one owner), incremental repack on the source
    (``remove_tenant``) and destination (``add_tenant``), both at queue
    generation boundaries.
    """

    HISTORY_CAP = 64

    def __init__(self, settings: Settings | None = None,
                 journal_path: "str | None" = None) -> None:
        self.settings = settings or get_settings()
        s = self.settings
        self.max_packs = max(int(getattr(s, "swell_max_packs", 1)), 1)
        self.pack_tenants = max(
            int(getattr(s, "swell_pack_tenants", 4)), 1)
        self._load_alpha = float(getattr(s, "swell_load_alpha", 0.2))
        self._stores: dict[str, EvidenceGraphStore] = {}
        self._lock = threading.Lock()
        self.generation = 0
        self.migrations = 0
        # tenant -> pack id (the single source of ownership truth:
        # every tenant appears exactly once, by construction)
        self._placement: dict[str, int] = {}
        self._packs: dict[int, MultiTenantScorer] = {}
        self._pack_built: dict[int, frozenset] = {}
        # per-tenant admitted-rows/s EWMA + the journal cursor sample it
        # was last advanced from
        self._loads: dict[str, float] = {}
        self._load_cursor: dict[str, tuple[int, float]] = {}
        self._history: collections.deque = collections.deque(
            maxlen=self.HISTORY_CAP)
        # graft-chaos seam: tests install a FaultInjector; migrate()
        # visits the "migrate" stage at each handoff boundary
        self.fault_injector = None
        self._fleet_journal = _FleetJournal(
            journal_path or getattr(s, "swell_journal_path", "") or None)
        self._recover_placement()

    # -- registration / placement ------------------------------------------

    def register(self, tenant: str, store: EvidenceGraphStore) -> None:
        with self._lock:
            old = self._stores.get(tenant)
            if old is not None and old is not store:
                raise ValueError(
                    f"tenant {tenant!r} already registered with a "
                    "different store")
            self._stores[tenant] = store
            if tenant not in self._placement:
                self._placement[tenant] = self._place_locked(tenant)

    def _place_locked(self, tenant: str) -> int:
        """Greedy bin-pack for a new tenant: the least-loaded pack with
        tenant capacity; a fresh pack when every open pack is full and
        the fleet has room; otherwise the least-loaded pack regardless
        (capacity is a target, not a hard wall — admission control owns
        hard limits)."""
        counts: dict[int, int] = {p: 0 for p in range(
            len(set(self._placement.values())))}
        loads: dict[int, float] = {}
        for t, p in self._placement.items():
            counts[p] = counts.get(p, 0) + 1
            loads[p] = loads.get(p, 0.0) + self._loads.get(t, 0.0)
        open_packs = sorted(counts)
        with_room = [p for p in open_packs
                     if counts[p] < self.pack_tenants]
        if with_room:
            return min(with_room,
                       key=lambda p: (loads.get(p, 0.0), counts[p], p))
        if len(open_packs) < self.max_packs:
            return (max(open_packs) + 1) if open_packs else 0
        if not open_packs:
            return 0
        return min(open_packs,
                   key=lambda p: (loads.get(p, 0.0), counts[p], p))

    def _recover_placement(self) -> None:
        """Roll the fleet WAL FORWARD: an intent record already moves
        ownership to the destination (the cursor handoff is in the
        record; the packs rebuild from stores, so a crash between the
        intent and any mutate boundary loses no data). After replay
        every migrated tenant has exactly one owner — the later of its
        records wins, and registration honors the recovered placement."""
        for rec in self._fleet_journal.replay():
            if rec.get("kind") in ("migrate_intent", "migrate_commit"):
                self._placement[str(rec["tenant"])] = int(rec["dst"])

    # -- pack building ------------------------------------------------------

    def _tenants_of_locked(self, pack_id: int) -> frozenset:
        return frozenset(t for t, p in self._placement.items()
                         if p == pack_id and t in self._stores)

    def fresh(self) -> bool:
        """True when every pack with placed tenants is built over
        exactly its current tenant set — the worker fast path's cheap
        staleness probe."""
        with self._lock:
            for pack_id in set(self._placement.values()):
                names = self._tenants_of_locked(pack_id)
                if not names:
                    continue
                if (self._packs.get(pack_id) is None
                        or self._pack_built.get(pack_id) != names):
                    return False
            return bool(self._stores)

    def scorer(self, tenant: "str | None" = None) -> MultiTenantScorer:
        """The pack serving ``tenant``, (re)built if its tenant set
        changed since the last build. ``tenant=None`` (back-compat:
        single-pack callers, benches) returns the lowest-numbered pack.
        A repack supersedes the old scorer (warm threads stopped;
        in-flight results were per-pack anyway)."""
        with self._lock:
            if tenant is None:
                pack_id = min(set(self._placement.values()), default=0)
            else:
                if tenant not in self._placement:
                    raise KeyError(f"tenant {tenant!r} not registered")
                pack_id = self._placement[tenant]
            return self._build_pack_locked(pack_id)

    def _build_pack_locked(self, pack_id: int) -> MultiTenantScorer:
        names = self._tenants_of_locked(pack_id)
        if not names:
            raise ValueError(f"no tenants placed on pack {pack_id}")
        cur = self._packs.get(pack_id)
        if cur is None or names != self._pack_built.get(pack_id):
            if cur is not None:
                cur.stop_warm(join=False)
                log.info("surge_repack", pack=pack_id,
                         tenants=sorted(names))
            pack = MultiTenantScorer(
                {t: self._stores[t] for t in sorted(names)},
                self.settings)
            # graft-swell satellite: stamp the pack identity into the
            # scorer's telemetry so N packs never alias one gauge series
            pack._scope_pack = str(pack_id)
            pack.scope.pack = str(pack_id)
            self._packs[pack_id] = pack
            self._pack_built[pack_id] = names
            self.generation += 1
            obs_metrics.FLEET_PACKS.set(float(len(self._packs)))
        return self._packs[pack_id]

    # -- per-tenant load estimation ----------------------------------------

    def sample_loads(self, now_s: "float | None" = None) -> dict:
        """Advance every tenant's admitted-rows/s EWMA from its store
        journal cursor (admitted rows land in the journal; the cursor
        delta over wall time is the admission rate the bin-packer and
        the fleet API report). Injectable clock for tests."""
        now = time.monotonic() if now_s is None else float(now_s)
        with self._lock:
            for tenant, store in self._stores.items():
                seq = int(store.journal_seq)
                prev = self._load_cursor.get(tenant)
                self._load_cursor[tenant] = (seq, now)
                if prev is None:
                    continue
                seq0, t0 = prev
                dt = now - t0
                if dt <= 0:
                    continue
                rate = max(seq - seq0, 0) / dt
                old = self._loads.get(tenant)
                a = self._load_alpha
                ewma = rate if old is None else (1 - a) * old + a * rate
                self._loads[tenant] = ewma
                obs_metrics.FLEET_TENANT_LOAD.set(ewma, tenant=tenant)
            return dict(self._loads)

    # -- live tenant migration ---------------------------------------------

    def _fault(self, stage: str) -> None:
        fi = self.fault_injector
        if fi is not None:
            fi.at(stage)

    def migrate(self, tenant: str, dst: int) -> dict:
        """Move one tenant between packs LIVE, exactly-once.

        Order (the shield's journal-before-mutate discipline, on the
        fleet WAL): (1) append the intent record — tenant, src, dst,
        and the store-journal CURSOR at handoff — and fsync; (2)
        incremental repack on the source (``remove_tenant``; the whole
        pack retires instead when the tenant was its last); (3) flip
        placement and adopt on the destination (``add_tenant`` when the
        pack is live, else the next ``scorer()`` builds it); (4) append
        the commit record. A crash at ANY boundary recovers to exactly
        one owner: replay rolls the intent forward, packs rebuild from
        stores, and the destination's first sync drains the tenant's
        journal from the recorded cursor — records are applied once.
        """
        with self._lock:
            if tenant not in self._stores:
                raise KeyError(f"tenant {tenant!r} not registered")
            dst = int(dst)
            if dst < 0 or dst >= self.max_packs:
                raise ValueError(
                    f"destination pack {dst} outside the fleet "
                    f"(max_packs={self.max_packs})")
            src = self._placement[tenant]
            if src == dst:
                return {"tenant": tenant, "src": src, "dst": dst,
                        "moved": False}
            store = self._stores[tenant]
            cursor = int(store.journal_seq)
            self._fleet_journal.append(
                {"kind": "migrate_intent", "tenant": tenant, "src": src,
                 "dst": dst, "cursor": cursor, "gen": self.generation})
            self._fault("migrate")          # crash at the journal boundary
            src_pack = self._packs.get(src)
            if src_pack is not None:
                if len(self._pack_built.get(src, ())) <= 1:
                    # last tenant: retire the whole pack, no repack
                    src_pack.stop_warm(join=False)
                    self._packs.pop(src, None)
                    self._pack_built.pop(src, None)
                else:
                    src_pack.remove_tenant(tenant)
                    self._pack_built[src] = \
                        self._pack_built[src] - {tenant}
            self._fault("migrate")          # crash at the repack boundary
            self._placement[tenant] = dst
            dst_pack = self._packs.get(dst)
            if dst_pack is not None:
                dst_pack.add_tenant(tenant, store)
                self._pack_built[dst] = \
                    self._pack_built.get(dst, frozenset()) | {tenant}
            self._fault("migrate")          # crash at the adopt boundary
            self._fleet_journal.append(
                {"kind": "migrate_commit", "tenant": tenant, "src": src,
                 "dst": dst, "cursor": cursor, "gen": self.generation})
            self.generation += 1
            self.migrations += 1
            self._history.append(
                {"event": "migrate", "tenant": tenant, "src": src,
                 "dst": dst, "cursor": cursor, "gen": self.generation})
            obs_metrics.FLEET_TENANT_MIGRATIONS.inc()
            obs_metrics.FLEET_PACKS.set(float(len(self._packs)))
        obs_scope.FLIGHT_RECORDER.note_event(
            "tenant_migrate", tenant=tenant, src=src, dst=dst,
            cursor=cursor)
        log.warning("tenant_migrated", tenant=tenant, src=src, dst=dst,
                    cursor=cursor)
        return {"tenant": tenant, "src": src, "dst": dst, "moved": True,
                "cursor": cursor}

    def note_scale(self, pack_id: int, decision: dict) -> None:
        """Record one ElasticController scale decision into the fleet
        history ring (the /api/v1/fleet forensic surface)."""
        if decision.get("action", "hold") == "hold":
            return
        with self._lock:
            self._history.append(
                {"event": decision["action"], "pack": int(pack_id),
                 "plan": decision.get("plan"), "gen": self.generation})

    # -- the fleet API surface ---------------------------------------------

    def fleet(self) -> dict:
        """Placement, per-tenant load estimates, and the scale/migration
        history ring — the GET /api/v1/fleet payload."""
        with self._lock:
            packs: dict[str, dict] = {}
            for pack_id in sorted(set(self._placement.values())):
                names = sorted(self._tenants_of_locked(pack_id))
                built = self._packs.get(pack_id) is not None
                packs[str(pack_id)] = {
                    "tenants": names,
                    "built": built,
                    "shards": (int(self._packs[pack_id]._graph_size())
                               if built else 0),
                }
            return {
                "packs": packs,
                "placement": dict(self._placement),
                "loads": {t: round(v, 3)
                          for t, v in self._loads.items()},
                "history": list(self._history),
                "generation": self.generation,
                "migrations": self.migrations,
                "max_packs": self.max_packs,
                "pack_tenants": self.pack_tenants,
            }
