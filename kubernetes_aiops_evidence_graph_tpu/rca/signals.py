"""CPU-side signal extraction: evidence list → condition vector.

Faithful re-implementation of the reference's signal fold + condition
checkers (rules_engine.py:265-410), extended with the four conditions the
reference declared but never implemented (SURVEY.md §3.6 defect 5). This is
the accuracy oracle the TPU backend is parity-tested against.
"""
from __future__ import annotations

from collections import defaultdict
from dataclasses import dataclass, field
from typing import Any, Iterable

import numpy as np

from .ruleset import (
    Cond,
    MEMORY_HIGH_PCT,
    MULTIPLE_PODS_THRESHOLD,
    NETWORK_ERRORS_THRESHOLD,
    NUM_CONDS,
    POD_NOT_READY_SECONDS,
    PROBLEM_POD_RESTARTS,
)

_IMAGE_PULL_REASONS = {"ImagePullBackOff", "ErrImagePull", "ImageInspectError"}
_CONFIG_REASONS = {"ContainerCannotRun", "CreateContainerConfigError"}
_NETWORK_LOG_PATTERNS = {"network", "connection", "timeout"}


@dataclass
class Signals:
    """The folded signal state (reference _init_signals, rules_engine.py:274-290)."""
    waiting_reasons: set[str] = field(default_factory=set)
    terminated_reasons: set[str] = field(default_factory=set)
    log_patterns: set[str] = field(default_factory=set)
    has_recent_deploy: bool = False
    has_image_change: bool = False
    memory_usage_high: bool = False
    cpu_throttling: bool = False
    hpa_at_max: bool = False
    latency_high: bool = False
    node_issues: dict[str, Any] = field(default_factory=dict)
    restart_count: int = 0
    error_count: int = 0
    network_error_count: int = 0
    pod_not_ready: bool = False
    readiness_probe_failing: bool = False
    problem_pods_by_node: dict[str, int] = field(default_factory=lambda: defaultdict(int))
    evidence_ids: list[str] = field(default_factory=list)
    max_signal_strength: float = 0.0


def _is_problem_pod(data: dict) -> bool:
    """Mirror of the collector's signal heuristic (kubernetes_collector.py:269-285)."""
    return bool(
        data.get("waiting_reason")
        or data.get("terminated_reason")
        or (data.get("restart_count", 0) or 0) > PROBLEM_POD_RESTARTS
        or data.get("ready") is False
    )


def extract_signals(evidence: Iterable[dict]) -> Signals:
    """Fold evidence dicts into Signals (rules_engine.py:292-357 semantics)."""
    s = Signals()
    for ev in evidence:
        ev_id = ev.get("id")
        if ev_id is not None:
            s.evidence_ids.append(str(ev_id))
        s.max_signal_strength = max(s.max_signal_strength, float(ev.get("signal_strength", 0) or 0))
        data = ev.get("data", {}) or {}
        ev_type = ev.get("evidence_type")
        if ev_type == "kubernetes_pod":
            _fold_pod(data, s)
        elif ev_type == "deploy_change":
            if data.get("is_recent_change"):
                s.has_recent_deploy = True
        elif ev_type == "image_change":
            if data.get("image_changed"):
                s.has_image_change = True
        elif ev_type == "log_signal":
            for pat in data.get("patterns_found", []) or []:
                s.log_patterns.add(pat)
            s.error_count += int(data.get("error_count", 0) or 0)
            s.network_error_count += int(data.get("network_error_count", 0) or 0)
        elif ev_type == "metric_signal":
            _fold_metric(data, s)
        elif ev_type == "kubernetes_node":
            _fold_node(data, s)
        elif ev_type == "kubernetes_hpa":
            if data.get("at_max") or data.get("hpa_at_max"):
                s.hpa_at_max = True
    return s


def _fold_pod(data: dict, s: Signals) -> None:
    if data.get("waiting_reason"):
        s.waiting_reasons.add(data["waiting_reason"])
    if data.get("terminated_reason"):
        s.terminated_reasons.add(data["terminated_reason"])
    s.restart_count = max(s.restart_count, int(data.get("restart_count", 0) or 0))
    if data.get("ready") is False and float(data.get("not_ready_seconds", 0) or 0) >= POD_NOT_READY_SECONDS:
        s.pod_not_ready = True
    if data.get("readiness_probe_failing"):
        s.readiness_probe_failing = True
    if _is_problem_pod(data) and data.get("node"):
        s.problem_pods_by_node[data["node"]] += 1


def _fold_metric(data: dict, s: Signals) -> None:
    """Reference _process_metric_evidence (rules_engine.py:337-350), with
    thresholds applied to the series eval value (the family's windowed
    statistic — utils/metricseries.EVAL_STAT) instead of the last sample,
    so spikes that receded and trends toward a limit still register."""
    from ..utils.metricseries import metric_eval
    query_name = data.get("query_name", "") or ""
    value = metric_eval(data)
    if "memory" in query_name and data.get("is_anomalous") \
            and value > MEMORY_HIGH_PCT:
        s.memory_usage_high = True
    if "hpa" in query_name and "max" in query_name and value >= 1:
        s.hpa_at_max = True
    if "latency" in query_name and value > 1:
        s.latency_high = True
    if "throttl" in query_name and value > 0.5:
        s.cpu_throttling = True


def _fold_node(data: dict, s: Signals) -> None:
    """Reference _process_node_evidence (rules_engine.py:352-357)."""
    conds = data.get("conditions", {}) or {}
    ready = conds.get("Ready", {})
    status = ready.get("status") if isinstance(ready, dict) else ready
    if status != "True":
        s.node_issues[data.get("name", "?")] = conds


def condition_vector(s: Signals) -> np.ndarray:
    """Evaluate the full condition vocabulary against folded signals.

    Matches reference _check_condition truth semantics (rules_engine.py:380-410)
    for the nine conditions that existed, plus the four fixed ones.
    """
    v = np.zeros(NUM_CONDS, dtype=bool)
    v[Cond.WAITING_CRASHLOOP] = "CrashLoopBackOff" in s.waiting_reasons
    v[Cond.WAITING_IMAGE_PULL] = bool(s.waiting_reasons & _IMAGE_PULL_REASONS)
    v[Cond.TERMINATED_OOM] = "OOMKilled" in s.terminated_reasons
    v[Cond.TERMINATED_CONFIG] = bool(s.terminated_reasons & _CONFIG_REASONS)
    v[Cond.RECENT_DEPLOY] = s.has_recent_deploy
    v[Cond.NO_RECENT_DEPLOY] = not s.has_recent_deploy
    v[Cond.MEMORY_USAGE_HIGH] = s.memory_usage_high
    v[Cond.HPA_AT_MAX] = s.hpa_at_max
    v[Cond.LATENCY_HIGH] = s.latency_high
    v[Cond.LOG_PATTERN_NETWORK] = bool(s.log_patterns & _NETWORK_LOG_PATTERNS)
    v[Cond.NODE_UNHEALTHY] = bool(s.node_issues)
    v[Cond.MULTIPLE_PODS_SAME_NODE] = (
        max(s.problem_pods_by_node.values(), default=0) >= MULTIPLE_PODS_THRESHOLD
    )
    v[Cond.POD_NOT_READY] = s.pod_not_ready
    v[Cond.READINESS_PROBE_FAILING] = s.readiness_probe_failing
    v[Cond.NETWORK_ERRORS_HIGH] = s.network_error_count >= NETWORK_ERRORS_THRESHOLD
    return v
