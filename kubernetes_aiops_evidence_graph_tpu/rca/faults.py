"""Deterministic fault injection for the serving hot path (graft-shield).

The shield's recovery claims are only as strong as the faults they were
proven against, so this harness injects failures at every stage of the
tick pipeline — from seeded schedules, so every chaos run is exactly
reproducible from its seed (the CI chaos job echoes the seed it drew).

Stages (where the hooks fire):

* ``staging``        — shield delta staging, before any state mutation
* ``dispatch``       — after the pending deltas are packed and drained,
                       before the fused tick runs (the staged values are
                       lost: recovery MUST replay, a bare retry cannot)
* ``pack``           — graft-intake: the PACKED delta buffers exist (the
                       columnar staged slab / the packed int payload) but
                       the tick has not run; deltas are already drained,
                       so this is dispatch-class — journal replay only
* ``execute``        — after the tick ran and the donated handles were
                       swapped (a device error / preemption mid-pipeline);
                       ``device_loss`` additionally corrupts the resident
                       arrays, simulating the donated buffers dying
* ``fetch``          — the device→host readback failed (state is intact:
                       an empty re-tick re-serves it)
* ``journal_append`` / ``snapshot_write`` — torn writes via the
                       rca/journal.py fault hook (crash mid-record)
* ``delta_values``   — value poisoning: NaN/inf stamped into the staged
                       feature rows (the finite guard must quarantine)
* ``stall``          — the tick completes but only after sleeping past the
                       watchdog timeout (fires at the ``execute`` hook)
* ``shard_loss``     — graft-heal: a PER-SHARD device fault on the
                       graph-sharded resident state. ``kind="shard_loss"``
                       corrupts exactly one shard's node block and raises
                       with the mesh position attached (the shield's
                       shard-loss classifier keys on it);
                       ``kind="shard_corrupt_silent"`` corrupts the block
                       and returns — the class only the per-shard
                       attestation fold can localize before it serves

graft-storm widened the harness past the tick path — the ingest and
learner paths previously had ZERO fault coverage:

* ``parse``          — the webhook payload-decode boundary
                       (app.ingest_batch entry): the batch is rejected,
                       nothing admitted/persisted, the client retries
* ``dedup``          — the batch dedup probe: MUST fail open (alerts are
                       never dropped by a broken window; the storage
                       UNIQUE-fingerprint backstop preserves parity)
* ``persist``        — the SQLite insert: failures walk the persist
                       circuit breaker (open → bounded spill journal →
                       half-open probe → replay)
* ``admit``          — the admission gate: MUST fail open (a broken gate
                       never drops alerts on its own)
* ``harvest`` / ``swap`` — the online-learning loop (learn/loop.py): a
                       faulted cycle is contained — serving params and
                       generation are untouched, the loop survives

Faults address the Nth *visit* of their stage and can repeat for several
consecutive visits (``repeats``) to force the shield past bounded retry
into the deeper degradation tiers.
"""
from __future__ import annotations

import time
from dataclasses import dataclass
from typing import Any, Iterable

import numpy as np

from ..observability import get_logger

log = get_logger("shield.faults")

TICK_STAGES = ("staging", "dispatch", "pack", "execute", "fetch",
               "journal_append", "snapshot_write", "delta_values",
               "shard_loss")
# graft-storm: the previously-uncovered halves of the pipeline
INGEST_STAGES = ("parse", "dedup", "persist", "admit")
LEARN_STAGES = ("harvest", "swap")
# graft-saga: the incident-lifecycle stage boundaries (the back half,
# verdict → remediation → verified closure). The tick pipeline reuses
# "execute" as a stage name; lifecycle hooks are distinct call sites
# (workflow steps + the two-phase executor), so the shared name never
# aliases — one injector drives one pipeline at a time.
#
# * ``collect``      — inside collect_evidence, before evidence persists
# * ``journal_put``  — engine boundary: the step ran, its journal commit
#                      has not (the classic lost-commit crash)
# * ``wf_execute``   — inside the two-phase executor: the CLUSTER
#                      MUTATION landed, the ledger result row has not —
#                      resume must reconcile, never re-fire
# * ``verify``       — inside verify_remediation, before the verdict
# * ``compensate``   — inside the saga compensation step
# * ``crash_restart``— immediately after a resumed run re-acquires the
#                      lease (a worker that dies again right away)
WORKFLOW_STAGES = ("collect", "journal_put", "wf_execute", "verify",
                   "compensate", "crash_restart")
# graft-swell: the tenant-migration handoff boundaries (SurgeServer
# ``migrate``). ONE stage name, three hook visits per migration — after
# the fleet-journal intent append, after the source pack's incremental
# repack, and after the destination adopt — so a seeded schedule can
# crash a migration at any boundary and the recovery replay must still
# land the tenant with exactly one owner.
MIGRATE_STAGES = ("migrate",)
STAGES = (TICK_STAGES + INGEST_STAGES + LEARN_STAGES + WORKFLOW_STAGES
          + MIGRATE_STAGES)

# value-corruption stages return poisoned data instead of raising
_POISON_STAGES = frozenset({"delta_values"})


class WorkflowCrash(BaseException):
    """A simulated worker death at a lifecycle stage boundary. Derives
    from BaseException ON PURPOSE: every per-step / per-incident handler
    catches Exception, and a crash must tear the whole run down exactly
    the way SIGKILL would — no retry, no audit row, no lease release.
    The chaos harness catches it at the process-boundary analog and
    resumes through the journal-replay path like a fresh worker."""

    def __init__(self, stage: str, visit: int):
        super().__init__(f"injected crash at {stage} (visit {visit})")
        self.stage = stage
        self.visit = visit


class InjectedFault(RuntimeError):
    """A scheduled failure. ``stage`` tells the shield what is suspect:
    faults at ``staging``/``journal_append``/``snapshot_write``/``fetch``
    leave the resident state coherent (bounded retry is sound); faults at
    ``dispatch``/``execute`` mean staged deltas or the donated state
    itself are gone and only journal-replay recovery restores parity."""

    def __init__(self, stage: str, kind: str, visit: int,
                 shard: "int | None" = None):
        msg = f"injected {kind} fault at {stage} (visit {visit})"
        if shard is not None:
            msg += f" [shard {shard}]"
        super().__init__(msg)
        self.stage = stage
        self.kind = kind
        self.visit = visit
        # graft-heal: mesh position the fault is localized to (None =
        # not shard-attributable) — the shield's classifier reads this
        self.shard = shard


@dataclass(frozen=True)
class Fault:
    stage: str          # one of STAGES
    at: int             # fires on the Nth visit of the stage (0-based)
    kind: str = "raise"  # raise | device_loss | corrupt_silent | poison |
    #                      stall | shard_loss | shard_corrupt_silent
    repeats: int = 1    # consecutive visits that fail (escalation depth)
    shard: int = 0      # graft-heal: target mesh position for shard kinds


class FaultInjector:
    """Deterministic schedule of Faults, consulted at the named hook
    points (scorer ``_fault_point``/``_fault_value`` + the journal's
    ``fault_hook``). Stateless apart from per-stage visit counters, so a
    replay of the same script with the same schedule faults identically."""

    def __init__(self, faults: Iterable[Fault] = (),
                 stall_seconds: float = 0.0) -> None:
        self.faults = list(faults)
        self.stall_seconds = stall_seconds
        self.visits: dict[str, int] = {}
        self.fired: list[tuple[str, str, int]] = []

    @classmethod
    def seeded(cls, seed: int, ticks: int, rate: float = 0.25,
               stages: tuple[str, ...] = STAGES,
               stall_seconds: float = 0.0,
               shards: int = 0) -> "FaultInjector":
        """Randomized-but-reproducible schedule: each stage draws fault
        visits over ``[0, ticks)`` at ``rate``. The same seed always
        yields the same schedule — chaos runs log the seed so any failure
        reproduces exactly. ``shards`` > 0 widens the pool with per-shard
        kinds: ``shard_loss`` draws target a random mesh position, and
        half of them go SILENT (corruption only the attestation fold can
        localize)."""
        rng = np.random.default_rng(seed)
        faults: list[Fault] = []
        for stage in stages:
            hits = rng.random(ticks) < rate
            for at in np.nonzero(hits)[0]:
                shard = 0
                if stage == "delta_values":
                    kind = "poison"
                elif stage == "shard_loss":
                    kind = ("shard_corrupt_silent"
                            if rng.random() < 0.5 else "shard_loss")
                    shard = int(rng.integers(0, max(shards, 1)))
                elif stage == "execute" and rng.random() < 0.5:
                    kind = "device_loss"
                elif stage in WORKFLOW_STAGES:
                    # lifecycle stages simulate worker DEATH, not a
                    # retryable step error — the resumer must drain them
                    kind = "crash"
                else:
                    kind = "raise"
                faults.append(Fault(stage=stage, at=int(at), kind=kind,
                                    shard=shard))
        return cls(faults, stall_seconds=stall_seconds)

    def _due(self, stage: str) -> "Fault | None":
        visit = self.visits.get(stage, 0)
        self.visits[stage] = visit + 1
        for f in self.faults:
            if f.stage == stage and f.at <= visit < f.at + f.repeats:
                return f
        return None

    # -- hook API (scorer/_shield/journal call these) ----------------------

    def at(self, stage: str, scorer: Any = None) -> None:
        """Raise (or corrupt-then-raise, or stall) if a fault is due at
        this visit of ``stage``; no-op otherwise."""
        f = self._due(stage)
        if f is None:
            return
        visit = self.visits[stage] - 1
        self.fired.append((stage, f.kind, visit))
        log.warning("fault_injected", stage=stage, kind=f.kind, visit=visit)
        if f.kind == "crash":
            raise WorkflowCrash(stage, visit)
        if f.kind == "stall":
            time.sleep(self.stall_seconds)
            return                      # completes, but past the watchdog
        if f.kind == "corrupt_silent" and scorer is not None:
            # the nastiest class: the device state dies but nothing
            # raises — only the finite guard at the verdict boundary can
            # catch it before garbage serves
            self._corrupt_resident(scorer)
            return
        if f.kind == "shard_corrupt_silent" and scorer is not None:
            # graft-heal: SILENT single-shard corruption — the rules fold
            # absorbs NaN through threshold compares, so only the
            # per-shard attestation fold at the next snapshot boundary
            # can localize (and repair) it before a wrong verdict serves
            self._corrupt_shard(scorer, f.shard)
            return
        if f.kind == "shard_loss" and scorer is not None:
            shard = self._corrupt_shard(scorer, f.shard)
            raise InjectedFault(stage, f.kind, visit, shard=shard)
        if f.kind == "device_loss" and scorer is not None:
            self._corrupt_resident(scorer)
        raise InjectedFault(stage, f.kind, visit)

    def poison(self, stage: str, value: np.ndarray) -> np.ndarray:
        """Return ``value`` with NaN/inf stamped in if a poison fault is
        due; the original array otherwise."""
        f = self._due(stage)
        if f is None or f.kind != "poison":
            return value
        visit = self.visits[stage] - 1
        self.fired.append((stage, "poison", visit))
        log.warning("fault_injected", stage=stage, kind="poison", visit=visit)
        bad = np.array(value, copy=True)
        if bad.size:
            # whole rows go non-finite: any poisoned row that is (or ever
            # becomes) evidence WILL surface at the verdict boundary — the
            # finite guard must catch it, not column luck
            bad.fill(np.nan)
            bad.reshape(-1)[0] = np.inf
        return bad

    def journal_hook(self, stage: str) -> None:
        """Adapter with the rca/journal.py ``fault_hook`` signature."""
        self.at(stage)

    # -- corruption --------------------------------------------------------

    @staticmethod
    def _corrupt_shard(scorer: Any, shard: int) -> int:
        """graft-heal: kill exactly ONE mesh position's node block — the
        feature rows owned by that shard go NaN while every other block
        stays bit-intact, so (a) the shard-loss classifier can localize
        the fault and (b) the attestation fold must flag exactly one
        shard. Returns the (wrapped) position actually corrupted."""
        import jax.numpy as jnp
        feats = getattr(scorer, "_features_dev", None)
        if feats is None:
            return 0
        g = max(int(scorer._graph_size()), 1) \
            if hasattr(scorer, "_graph_size") else 1
        shard = int(shard) % g
        rows = feats.shape[0] // g
        scorer._features_dev = feats.at[
            shard * rows:(shard + 1) * rows].set(jnp.nan)
        return shard

    @staticmethod
    def _corrupt_resident(scorer: Any) -> None:
        """Simulate the donated resident buffers dying with the device:
        the feature matrix (the only f32 resident input every verdict
        folds) is replaced by NaNs, so any path that keeps serving from
        this state is guaranteed to be caught by the finite guard."""
        import jax.numpy as jnp
        feats = getattr(scorer, "_features_dev", None)
        if feats is not None:
            scorer._features_dev = jnp.full(
                feats.shape, jnp.nan, dtype=feats.dtype)


class MutationRecorder:
    """graft-saga counting seam: wraps a cluster backend and records
    every cluster-MUTATING call as (method, *str(args)). The chaos
    sweeps assert exactly-once remediation on this ledger — a crash
    anywhere in the lifecycle (including between the cluster mutation
    and the journal commit) must yield ZERO duplicate mutations across
    all resume cycles. Reads pass through untouched."""

    MUTATORS = frozenset({
        "delete_pod", "restart_deployment", "rollback_deployment",
        "scale_deployment", "cordon_node", "uncordon_node",
    })

    def __init__(self, backend: Any) -> None:
        self._backend = backend
        self.calls: list[tuple] = []

    def __getattr__(self, name: str) -> Any:
        attr = getattr(self._backend, name)
        if name in self.MUTATORS and callable(attr):
            def wrapped(*a: Any, _attr=attr, _name=name, **k: Any) -> Any:
                self.calls.append((_name,) + tuple(str(x) for x in a))
                return _attr(*a, **k)
            return wrapped
        return attr

    def duplicates(self) -> list[tuple]:
        from collections import Counter
        return [c for c, n in Counter(self.calls).items() if n > 1]
