"""graft-swell: load-driven elastic serving meshes.

graft-heal (rca/heal.py + shield.mesh_heal) built the expensive
machinery for moving the resident serving state between mesh layouts —
WAL-journaled ``adopt_mesh`` at a queue generation boundary,
``warm_mesh`` pre-compilation so the move pays an upload, never a
compile, and a bit-parity contract proven by the heal tests. But only
device FAILURE triggered it. This module generalizes the trigger to
LOAD: an :class:`ElasticController` consumes the gauges graft-scope
already exports for every serving pack —

- pipeline occupancy (dispatched-but-unfetched ticks / pipeline depth)
  and stall seconds (time blocked for a pipeline slot),
- the admission layer's shed-ratio EWMA (demand the gate is refusing),
- roofline drift (achieved-bytes/s EWMA vs the session high-water
  mark: a tick running at its bandwidth ceiling cannot absorb more
  load at the current shard count)

— and drives hysteresis+dwell-gated D→D' decisions through the
EXISTING heal seams (``shield.scale_mesh``). The two-threshold + dwell
gate is ingestion/admission.StormMode's pattern verbatim: sustained
pressure for ``elastic_dwell_s`` scales up, sustained calm scales
down, and a flapping signal can never flap the mesh.

Scale-event discipline (the whole point of reusing the heal seams):

1. ``prewarm(d_new)`` compiles every serving-reachable tick variant at
   the TARGET shard count on a background warm thread (the scorer's
   ``warm_mesh`` seam — cooperative-cancel, compile-cache keyed), so
2. ``shield.scale_mesh(d_new)`` — WAL-journal FIRST, then
   ``adopt_mesh`` at a queue generation boundary — pays buffer uploads
   only. Zero XLA compiles inside the armed scale window is a CI leg
   (KAEG_COMPILE_FENCE=1), not a hope.
3. Bit-parity holds across D→D'→D: rules verdicts bit-identical, GNN
   verdicts verdict-identical, ppermute census exactly
   ``(LAYERS+1)·D'`` — the same contract the heal tests pin.

The controller never spawns its own thread: ``observe()`` is called
from whatever cadence the host already has (the workflow worker's
absorb loop, a bench harness, a test with a fake clock), mirroring how
StormMode is fed by the admission gate.
"""
from __future__ import annotations

import threading
import time

from ..config import get_settings
from ..observability import metrics as obs_metrics
from ..observability import scope as obs_scope
from ..observability.logging import get_logger

log = get_logger("elastic")


class _HysteresisGate:
    """Two-threshold + dwell gate (the StormMode pattern, direction-
    agnostic): ``update(hot)`` feeds one boolean pressure observation
    and returns True exactly once per sustained-entry — the caller
    resets by the act of scaling (which changes the signal)."""

    def __init__(self, dwell_s: float, clock=time.monotonic) -> None:
        self.dwell_s = float(dwell_s)
        self._clock = clock
        self._since: float | None = None

    def update(self, hot: bool) -> bool:
        now = self._clock()
        if not hot:
            self._since = None
            return False
        if self._since is None:
            self._since = now
        return now - self._since >= self.dwell_s

    def reset(self) -> None:
        self._since = None


class ElasticController:
    """Load-driven D→D' scale decisions for ONE shielded serving pack.

    ``observe()`` samples the pack's pressure signals, feeds the up/down
    hysteresis gates, and — when a gate fires and the cooldown has
    passed — pre-warms the target mesh and executes the reshard through
    ``shield.scale_mesh``. All decisions ride the divisor ladder: D'
    must divide ``padded_nodes`` and fit the non-excluded device count,
    so the reshard is always exact (no re-padding, bit-parity safe).
    """

    def __init__(self, shield, settings=None, admission=None,
                 clock=time.monotonic) -> None:
        self.settings = settings or get_settings()
        self.shield = shield
        self.admission = admission
        self._clock = clock
        s = self.settings
        self.enabled = bool(getattr(s, "elastic_enabled", False))
        self.cooldown_s = float(getattr(s, "elastic_cooldown_s", 30.0))
        dwell = float(getattr(s, "elastic_dwell_s", 10.0))
        self._up = _HysteresisGate(dwell, clock)
        self._down = _HysteresisGate(dwell, clock)
        self._lock = threading.Lock()
        self._last_scale_t: float | None = None
        self._last_stall = 0.0
        self.scale_ups = 0
        self.scale_downs = 0
        self.decisions = 0

    # -- signals -----------------------------------------------------------

    def signals(self) -> dict:
        """One pressure sample from the pack's existing telemetry — all
        plain attribute/EWMA reads, no device syncs, no gauge-registry
        round-trips."""
        sc = self.shield.scorer
        depth = max(int(getattr(sc, "pipeline_depth", 1)), 1)
        occupancy = len(getattr(sc, "_inflight", ())) / depth
        stall_total = float(getattr(sc, "stall_seconds", 0.0))
        stall_delta = max(stall_total - self._last_stall, 0.0)
        self._last_stall = stall_total
        shed = 0.0
        if self.admission is not None:
            shed = float(self.admission.stats().get("shed_ewma", 0.0))
        entry = getattr(sc, "_scope_entry", "streaming.rules_tick")
        pack = getattr(sc, "_scope_pack", "0")
        achieved = obs_scope.ROOFLINE.achieved(entry, pack)
        best = obs_scope.ROOFLINE.best(entry, pack)
        drift = (achieved / best) if best else 0.0
        return {"occupancy": occupancy, "stall_delta_s": stall_delta,
                "shed_ewma": shed, "roofline_drift": drift,
                "shards": int(sc._graph_size())}

    def _hot(self, sig: dict) -> bool:
        s = self.settings
        return (sig["occupancy"] >= float(
                    getattr(s, "elastic_up_occupancy", 0.75))
                or sig["shed_ewma"] >= float(
                    getattr(s, "elastic_up_shed", 0.05))
                or sig["stall_delta_s"] > 0.0
                or sig["roofline_drift"] >= float(
                    getattr(s, "elastic_up_roofline", 0.85)))

    def _cold(self, sig: dict) -> bool:
        s = self.settings
        return (sig["occupancy"] <= float(
                    getattr(s, "elastic_down_occupancy", 0.25))
                and sig["shed_ewma"] <= float(
                    getattr(s, "elastic_down_shed", 0.005))
                and sig["stall_delta_s"] == 0.0
                and (sig["roofline_drift"] <= float(
                    getattr(s, "elastic_down_roofline", 0.30))
                    or sig["roofline_drift"] == 0.0))

    # -- the divisor ladder ------------------------------------------------

    def ladder(self) -> tuple[int, ...]:
        """Viable shard counts: divisors of the pack's ``padded_nodes``
        that fit within the non-excluded device count, ascending."""
        import jax
        sc = self.shield.scorer
        pn = int(sc.snapshot.padded_nodes)
        avail = len(jax.devices()) - len(
            getattr(self.shield, "_mesh_excluded", ()))
        return tuple(d for d in range(1, max(avail, 1) + 1)
                     if pn % d == 0)

    def _step(self, direction: int) -> int | None:
        """Next rung of the ladder from the CURRENT shard count (+1 =
        up, -1 = down); None at the ladder's end."""
        rungs = self.ladder()
        cur = int(self.shield.scorer._graph_size())
        if direction > 0:
            bigger = [d for d in rungs if d > cur]
            return bigger[0] if bigger else None
        smaller = [d for d in rungs if d < cur]
        return smaller[-1] if smaller else None

    # -- execution ---------------------------------------------------------

    def prewarm(self, target_shards: int,
                delta_sizes=(64,), row_sizes=(4,)) -> None:
        """Compile the serving tick variants at the TARGET shard count
        BEFORE the scale event, on the calling thread, through the same
        ``warm_mesh`` seam graft-heal proved — the subsequent
        ``scale_mesh`` then pays an upload, never a compile."""
        from . import heal as heal_mod
        excluded = getattr(self.shield, "_mesh_excluded", ())
        mesh = heal_mod.survivor_mesh(int(target_shards), excluded)
        scorer = self.shield.scorer
        scorer.warm_mesh(mesh, delta_sizes=tuple(delta_sizes),
                         row_sizes=tuple(row_sizes))

    def _cooled(self, now: float) -> bool:
        return (self._last_scale_t is None
                or now - self._last_scale_t >= self.cooldown_s)

    def observe(self) -> dict:
        """Feed one pressure sample; possibly execute a scale event.
        Returns the decision record (also appended to the fleet history
        by the owning SurgeServer)."""
        with self._lock:
            self.decisions += 1
            sig = self.signals()
            fire_up = self._up.update(self._hot(sig))
            fire_down = self._down.update(self._cold(sig))
            now = self._clock()
            decision = {"action": "hold", **sig}
            if not self.enabled:
                return decision
            if fire_up and self._cooled(now):
                target = self._step(+1)
                if target is not None:
                    decision = self._scale(target, "up", now, sig)
            elif fire_down and not fire_up and self._cooled(now):
                target = self._step(-1)
                if target is not None:
                    decision = self._scale(target, "down", now, sig)
            return decision

    def _scale(self, target: int, direction: str, now: float,
               sig: dict) -> dict:
        """Caller holds ``self._lock``. Pre-warm, then reshard through
        the WAL-journaled seam; both gates reset so the next decision
        needs a fresh sustained signal."""
        self.prewarm(target)
        plan = self.shield.scale_mesh(target)
        self._up.reset()
        self._down.reset()
        self._last_scale_t = now
        if plan is None:
            return {"action": "hold", **sig}
        if direction == "up":
            self.scale_ups += 1
        else:
            self.scale_downs += 1
        obs_metrics.ELASTIC_SCALE_DECISIONS.inc(direction=direction)
        log.warning("elastic_scale", direction=direction,
                    from_shards=plan["from_shards"],
                    to_shards=plan["shards"],
                    occupancy=round(sig["occupancy"], 3),
                    shed_ewma=round(sig["shed_ewma"], 4))
        return {"action": f"scale_{direction}", "plan": plan, **sig}

    def stats(self) -> dict:
        with self._lock:
            return {"enabled": self.enabled,
                    "decisions": self.decisions,
                    "scale_ups": self.scale_ups,
                    "scale_downs": self.scale_downs,
                    "last_scale_t": self._last_scale_t}
