"""graft-heal: elastic shard-loss survival for the resident serving mesh.

graft-fleet (PR 7) sharded the resident serving state across a ``(1 x D)``
mesh and graft-shield (PR 6) made it crash-consistent — but the two never
composed into device-level fault tolerance: a dead chip in the mesh was
indistinguishable from total state loss, and the shield's only rungs were
journal replay at the *same* D or a full store rebuild. This module is the
missing composition, four pieces:

1. **Shard-loss classification** (:class:`ShardHealthTracker`). A
   per-mesh-position :class:`~..ingestion.admission.CircuitBreaker`:
   transient device faults reset on the next clean pass (the existing
   retry/replay rungs handle them), while N consecutive failures
   localized to ONE mesh position open that position's breaker — the
   "persistently failed shard" verdict the shield's new ``mesh_heal``
   rung keys on. Health is surfaced in the ``aiops_mesh_*`` gauges and
   the flight ring.

2. **Reshard planning** (:func:`plan_reshard` / :func:`survivor_mesh`).
   D' is the largest shard count below D that (a) the survivor device
   pool can carry and (b) the padded node bucket divides over — the same
   divisibility contract ``StreamingScorer._graph_sharded`` already
   enforces, so the healed state is exactly the state a fresh D' build
   would shard. D' = 1 degrades to single-device serving (mesh ``None``),
   the graceful floor.

3. **Per-shard state attestation** (:func:`attest_fold` /
   :func:`attest_host`). A jitted modular-checksum fold over the
   node-addressed resident arrays, computed per shard block and compared
   against the SAME fold of the host-truth mirrors at snapshot
   generation boundaries — silent per-shard corruption (the fault class
   today's whole-state nonfinite backstop can only catch after it serves
   a wrong verdict) is detected and localized to the one shard that must
   heal. Registered audit entrypoint (``heal.attest_fold``) with a
   zero-collective CostSpec at D=1; when sharded the fold is one small
   per-shard reduce, no psum.

4. **Re-expansion.** The failed device's breaker cools down into its
   half-open probe; the shield grows D' back to D at a queue generation
   boundary (graft-evolve's hot-swap discipline: the flip happens under
   ``serve_lock``, in-flight ticks complete on the old mesh and are
   superseded) and the probe either closes the breaker on the next clean
   pass or re-opens it — one failure after a probe re-heals immediately.

Both the heal and the re-expansion are WAL-journaled (``mesh_heal``
records carry a monotonic ``heal_gen``) BEFORE they apply, so a crash at
any point recovers to a consistent shard count: the snapshot records the
mesh shape it was captured at, and replay re-applies any newer heal
records in file order (rca/shield.py).
"""
from __future__ import annotations

import threading
from functools import lru_cache, partial

import numpy as np

import jax
import jax.numpy as jnp

from ..ingestion.admission import CircuitBreaker
from ..observability import get_logger
from ..observability import metrics as obs_metrics
from ..observability import scope as obs_scope

log = get_logger("heal")


# -- reshard planning -------------------------------------------------------

def plan_reshard(padded_nodes: int, shards: int, survivors: int) -> int:
    """Largest viable shard count D' < ``shards``: the survivor pool must
    carry it and the padded node bucket must divide over it (the
    ``_graph_sharded`` contract — a non-dividing D' would silently fall
    back to single-device, which the plan makes explicit instead by
    skipping it). Returns 1 (single-device serving, mesh ``None``) when
    no sharded layout survives."""
    for d in range(min(int(shards) - 1, int(survivors)), 1, -1):
        if padded_nodes % d == 0:
            return d
    return 1


@lru_cache(maxsize=None)
def survivor_mesh(shards: int, exclude: tuple[int, ...] = ()):
    """(1 x shards) serving mesh over the device pool MINUS the excluded
    device indices (the classified-dead chips). ``None`` when shards <= 1
    (single-device serving) or the survivor pool cannot carry the axis.
    Cached per (shards, exclude) so a heal→re-expand cycle back to the
    same layout reuses the mesh object (and through it the lru-cached
    compiled ticks)."""
    if shards <= 1:
        return None
    from jax.sharding import Mesh
    dead = set(int(i) for i in exclude)
    devices = [d for i, d in enumerate(jax.devices()) if i not in dead]
    if len(devices) < shards:
        return None
    arr = np.asarray(devices[:shards]).reshape(1, shards)
    return Mesh(arr, axis_names=("dp", "graph"))


def device_index(device) -> int:
    """Global index of ``device`` in the process device pool — the stable
    identity health/exclusion bookkeeping is keyed by (mesh positions
    shift across heals; devices do not)."""
    for i, d in enumerate(jax.devices()):
        if d == device:
            return i
    raise ValueError(f"device {device} not in the local pool")


# -- per-shard state attestation --------------------------------------------

@partial(jax.jit, static_argnames=("shards",))
def attest_fold(*arrays, shards: int):
    """Per-shard modular checksum of node-addressed resident arrays:
    float tables bitcast to int32 (bit-exact — NaN payloads included, so
    a poisoned block can never checksum clean), each array reshaped into
    its ``shards`` contiguous node blocks and folded with a wraparound
    uint32 sum (commutative — shard-local accumulation order is free).
    Returns ``[num_arrays, shards]`` uint32. At D=1 this is one
    whole-state fold with zero collectives (the registered CostSpec);
    sharded, each block's fold is shard-local — no psum, only the tiny
    [shards] result leaves the device."""
    sums = []
    for a in arrays:
        if jnp.issubdtype(a.dtype, jnp.floating):
            a = jax.lax.bitcast_convert_type(
                a.astype(jnp.float32), jnp.int32)
        blocks = a.astype(jnp.int32).reshape(shards, -1).astype(jnp.uint32)
        sums.append(blocks.sum(axis=1, dtype=jnp.uint32))
    return jnp.stack(sums)


def attest_host(arrays, shards: int) -> np.ndarray:
    """Host-side oracle of :func:`attest_fold` over the host-truth
    mirrors — the comparison baseline (the host copies are authoritative
    and bit-identical to the device state by the streaming mirror
    contract, rca/streaming.capture_host_state)."""
    out = []
    for a in arrays:
        a = np.ascontiguousarray(a)
        if a.dtype.kind == "f":
            a = np.ascontiguousarray(a.astype(np.float32)).view(np.int32)
        else:
            a = a.astype(np.int32)
        blocks = a.reshape(shards, -1).astype(np.uint32)
        out.append(blocks.sum(axis=1, dtype=np.uint32))
    return np.stack(out)


# -- shard-loss classification ----------------------------------------------

class ShardHealthTracker:
    """Per-mesh-position failure classification over the existing
    CircuitBreaker machinery (graft-storm).

    ``record_failure(pos)`` feeds a shard-localized fault into that
    position's breaker: N consecutive failures open it — the
    "persistently failed shard" verdict (:meth:`failed_position`). A
    clean guarded pass resets every live breaker (transient faults never
    accumulate across healthy ticks). On heal, the failed position's
    breaker moves to the EXCLUDED table keyed by its global device index
    (positions shift with the mesh; devices do not) where its cooldown
    gates the re-expansion probe: ``can_reexpand()`` is the half-open
    transition, and after :meth:`note_reexpanded` the probing breaker
    rides the device's new mesh position half-open — one more failure
    re-opens it (immediate re-heal), one clean pass closes it."""

    def __init__(self, failure_threshold: int = 3,
                 cooldown_s: float = 5.0) -> None:
        self.failure_threshold = max(int(failure_threshold), 1)
        self.cooldown_s = float(cooldown_s)
        self._lock = threading.Lock()
        self._live: dict[int, CircuitBreaker] = {}       # mesh position
        self._excluded: dict[int, CircuitBreaker] = {}   # device index
        self.shard_failures = 0

    def _breaker(self, pos: int) -> CircuitBreaker:
        b = self._live.get(pos)
        if b is None:
            b = self._live[pos] = CircuitBreaker(
                f"mesh_shard_{pos}",
                failure_threshold=self.failure_threshold,
                cooldown_s=self.cooldown_s)
        return b

    def record_failure(self, pos: int) -> str:
        """One shard-localized fault at mesh position ``pos``; returns
        the breaker state after recording (``open`` = classified)."""
        pos = int(pos)
        with self._lock:
            b = self._breaker(pos)
        b.record_failure()
        self.shard_failures += 1
        obs_metrics.MESH_SHARD_FAILURES.inc(shard=str(pos))
        obs_metrics.MESH_SHARD_HEALTH.set(
            0.0 if b.state == "open" else 1.0, shard=str(pos))
        obs_scope.FLIGHT_RECORDER.note_event(
            "shard_fault", shard=pos, state=b.state,
            failures=b.failures)
        return b.state

    def record_clean_pass(self) -> None:
        """A guarded pass with zero failures: consecutive-failure counts
        reset (transient ≠ persistent), half-open probes close, and
        fully-healthy breakers are pruned."""
        with self._lock:
            live = list(self._live.items())
        for pos, b in live:
            closing = b.state == "half_open"
            b.record_success()
            obs_metrics.MESH_SHARD_HEALTH.set(1.0, shard=str(pos))
            if closing:
                obs_scope.FLIGHT_RECORDER.note_event(
                    "shard_probe_closed", shard=pos)
            with self._lock:
                if b.state == "closed" and b.failures == 0:
                    self._live.pop(pos, None)

    def failed_position(self, exclude: tuple[int, ...] = ()) -> "int | None":
        """First mesh position classified as persistently failed (breaker
        open), skipping positions already excluded by a prior heal."""
        with self._lock:
            for pos in sorted(self._live):
                if pos in exclude:
                    continue
                if self._live[pos].state == "open":
                    return pos
        return None

    def exclude(self, pos: int, dev_idx: int) -> None:
        """Heal applied: move the failed position's breaker to the
        excluded table under its global device index and reset the live
        position space (positions shift with the new mesh)."""
        with self._lock:
            b = self._live.pop(int(pos), None)
            self._live.clear()
            if b is None:
                b = CircuitBreaker(
                    f"mesh_device_{dev_idx}",
                    failure_threshold=self.failure_threshold,
                    cooldown_s=self.cooldown_s)
                b.record_failure()
                for _ in range(self.failure_threshold - 1):
                    b.record_failure()
            self._excluded[int(dev_idx)] = b
        obs_metrics.MESH_SHARD_HEALTH.set(0.0, shard=str(dev_idx))

    def excluded_devices(self) -> tuple[int, ...]:
        with self._lock:
            return tuple(sorted(self._excluded))

    def can_reexpand(self) -> bool:
        """True when EVERY excluded device's breaker admits its half-open
        probe (cooldown elapsed) — the re-expansion gate."""
        with self._lock:
            excluded = list(self._excluded.values())
        # a breaker already sitting half-open (its probe admitted on an
        # earlier poll that another device then vetoed) counts as ready —
        # allow() alone would wedge multi-device re-expansion forever
        return bool(excluded) and all(
            b.state == "half_open" or b.allow() for b in excluded)

    def note_reexpanded(self, dev_to_pos: dict[int, int]) -> None:
        """Re-expansion applied: the probing breakers ride their devices'
        new mesh positions half-open — the next clean pass closes them,
        the next failure re-opens (immediate re-heal, no fresh N-count)."""
        with self._lock:
            for dev, b in list(self._excluded.items()):
                pos = dev_to_pos.get(dev)
                if pos is not None:
                    self._live[pos] = b
            self._excluded.clear()

    def stats(self) -> dict:
        with self._lock:
            return {
                "live": {p: b.state for p, b in self._live.items()},
                "excluded": {d: b.state for d, b in self._excluded.items()},
                "shard_failures": self.shard_failures,
            }
