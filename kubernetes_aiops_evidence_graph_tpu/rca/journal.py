"""Crash-consistent persistence for the resident serving state (graft-shield).

Two artifacts, both host-side and both O(what they carry):

* **Write-ahead delta journal** (``<dir>/deltas.wal``) — every store-journal
  record batch the shield is about to apply to the donated device state is
  appended and fsync'd FIRST, so a crash mid-tick can always be replayed.
  Appends are O(delta), never O(N). Each record is framed
  ``[u32 length][u32 crc32][pickle payload]``; the per-record checksum is
  what lets recovery detect a torn tail (a crash mid-append) and truncate
  back to the last durable record instead of failing or replaying garbage.

* **State snapshot** (``<dir>/state.snap``) — a periodic full capture of the
  scorer's host bookkeeping plus the packed device arrays, written
  atomically (temp file + fsync + ``os.replace``) so a crash mid-snapshot
  leaves the PREVIOUS snapshot intact. The snapshot payload carries its own
  crc frame too.

Recovery = load last snapshot + replay the journal suffix (batches whose
store-journal seq range postdates the snapshot). Replay applies the same
records through the same scorer mutation methods, so the recovered state is
bit-identical to the pre-fault state — and strictly cheaper than a full
``_rebuild()``, which re-tensorizes the whole store. Batches may appear
twice after an append retry; application is idempotent MERGE (the store
journal's own replay contract), so duplicates are harmless.

``fault_hook`` is the seam the deterministic fault harness (rca/faults.py)
uses to crash writes mid-record: the hook runs after the header bytes but
before the payload+fsync, producing exactly the torn tail the checksum
logic must survive.
"""
from __future__ import annotations

import os
import pickle
import struct
import threading
import zlib
from dataclasses import dataclass, field
from typing import Any, Callable, Sequence

from ..observability import get_logger

log = get_logger("shield.journal")

_FRAME = struct.Struct("<II")          # (payload length, crc32)

WAL_NAME = "deltas.wal"
SNAP_NAME = "state.snap"


@dataclass
class JournalBatch:
    """One appended delta batch: the store-journal records staged for one
    tick, plus the seq range they cover (``seq_hi`` = the store journal's
    cursor after this batch). ``kind`` is ``deltas`` for replayable
    batches and ``quarantine`` for audit markers (a batch whose staged
    values produced non-finite verdicts; its RECORDS are store-truth and
    replay clean — the marker records the incident, it does not skip)."""
    kind: str
    seq_lo: int
    seq_hi: int
    recs: list = field(default_factory=list)
    meta: dict = field(default_factory=dict)


def _write_frame(f, payload: bytes,
                 fault_hook: "Callable[[str], None] | None" = None,
                 stage: str = "journal_append", sync: bool = True) -> int:
    header = _FRAME.pack(len(payload), zlib.crc32(payload))
    f.write(header)
    if fault_hook is not None:
        # crash point BETWEEN header and payload: the torn-tail shape a
        # real mid-append crash produces (header present, payload short)
        fault_hook(stage)
    f.write(payload)
    if sync:
        f.flush()
        os.fsync(f.fileno())
    return len(header) + len(payload)


def _read_frames(path: str) -> tuple[list[bytes], int, int]:
    """(payloads, bytes of valid prefix, torn records dropped). Stops at
    the first short/corrupt frame — everything after a bad checksum is
    untrusted, and a crash can only tear the tail."""
    payloads: list[bytes] = []
    if not os.path.exists(path):
        return payloads, 0, 0
    data = open(path, "rb").read()
    off = 0
    torn = 0
    while off < len(data):
        if off + _FRAME.size > len(data):
            torn = 1
            break
        length, crc = _FRAME.unpack_from(data, off)
        start = off + _FRAME.size
        end = start + length
        if end > len(data) or zlib.crc32(data[start:end]) != crc:
            torn = 1
            break
        payloads.append(data[start:end])
        off = end
    return payloads, off, torn


class DeltaJournal:
    """Append-only WAL + atomic snapshot store under one directory."""

    def __init__(self, directory: str,
                 fault_hook: "Callable[[str], None] | None" = None,
                 fsync_every: int = 1) -> None:
        self.dir = directory
        os.makedirs(directory, exist_ok=True)
        self.wal_path = os.path.join(directory, WAL_NAME)
        self.snap_path = os.path.join(directory, SNAP_NAME)
        self.fault_hook = fault_hook
        # bounded group commit: every append is written+flushed, but the
        # fsync may be deferred for up to `fsync_every` batches (1 =
        # strict per-batch fsync). The data-at-risk window is bounded to
        # that many batches AND only matters for whole-host crashes — the
        # donated-state fault model (device fault / poisoned delta /
        # executor crash) keeps the host alive, where the page cache and
        # the store's own bounded journal still cover the unsynced tail.
        # Quarantine markers, snapshots, and compaction always fsync.
        self.fsync_every = max(int(fsync_every), 1)
        self._unsynced = 0
        self.appended_batches = 0
        self.appended_bytes = 0
        self.torn_truncations = 0
        # serializes WAL file ops: the shield persists snapshots (and
        # compacts) on a background writer thread while serving appends
        self._io_lock = threading.Lock()
        self._wal_f = open(self.wal_path, "ab")

    # -- write-ahead log ---------------------------------------------------

    def append(self, recs: Sequence[tuple], seq_lo: int, seq_hi: int,
               kind: str = "deltas", force_sync: bool = False,
               **meta: Any) -> int:
        """Append one batch (group-committed fsync, see __init__);
        returns bytes written. O(delta)."""
        payload = pickle.dumps(
            {"kind": kind, "seq_lo": int(seq_lo), "seq_hi": int(seq_hi),
             "recs": list(recs), "meta": meta},
            protocol=pickle.HIGHEST_PROTOCOL)
        with self._io_lock:
            self._unsynced += 1
            sync = force_sync or self._unsynced >= self.fsync_every
            n = _write_frame(self._wal_f, payload, self.fault_hook,
                             sync=sync)
            if not sync:
                self._wal_f.flush()
            else:
                self._unsynced = 0
        self.appended_batches += 1
        self.appended_bytes += n
        return n

    def fsync(self) -> None:
        with self._io_lock:
            self._wal_f.flush()
            os.fsync(self._wal_f.fileno())
            self._unsynced = 0

    def mark_quarantined(self, seq_lo: int, seq_hi: int, reason: str) -> int:
        """Audit marker: the batch covering [seq_lo, seq_hi] carried staged
        values that produced non-finite verdicts and was re-ticked from
        replayed (store-truth) state instead. Always fsync'd — an audit
        record that can vanish is not an audit record."""
        return self.append((), seq_lo, seq_hi, kind="quarantine",
                           force_sync=True, reason=reason)

    def read(self) -> tuple[list[JournalBatch], int]:
        """(batches in append order, torn records truncated). A torn or
        checksum-failing tail is physically truncated off the file so the
        next append extends a valid log."""
        with self._io_lock:
            self._wal_f.flush()
            payloads, valid, torn = _read_frames(self.wal_path)
            batches: list[JournalBatch] = []
            offset = 0                 # bytes of the decodable prefix
            for p in payloads:
                try:
                    d = pickle.loads(p)
                except (pickle.UnpicklingError, EOFError, ValueError,
                        AttributeError) as exc:
                    log.warning("wal_record_unreadable", error=str(exc))
                    torn = 1
                    valid = offset     # keep only the decodable prefix
                    break
                offset += _FRAME.size + len(p)
                batches.append(JournalBatch(
                    kind=d["kind"], seq_lo=d["seq_lo"], seq_hi=d["seq_hi"],
                    recs=d["recs"], meta=d.get("meta", {})))
            if torn:
                self.torn_truncations += 1
                log.warning("wal_torn_tail_truncated", valid_bytes=valid)
                self._wal_f.close()
                with open(self.wal_path, "rb+") as f:
                    f.truncate(valid)
                    f.flush()
                    os.fsync(f.fileno())
                self._wal_f = open(self.wal_path, "ab")
        return batches, torn

    def compact(self, through_seq: int,
                through_params_gen: "int | None" = None,
                through_heal_gen: "int | None" = None) -> None:
        """Drop batches fully covered by a snapshot at ``through_seq``
        (rewrite-and-replace, atomic): after a snapshot the prefix is dead
        weight and replay cost must stay O(suffix), not O(history).

        ``params_swap`` records (graft-evolve: a hot checkpoint swap
        journaled ahead of its application) are NOT covered by a store-seq
        horizon — a swap can land at the same store seq as a snapshot
        captured BEFORE it, and dropping its record would recover the old
        generation. They compact by their own monotonic generation:
        records at generations the snapshot already carries
        (``<= through_params_gen``) are dead weight; newer ones survive.
        ``None`` keeps every swap record (a shield that never learned the
        snapshot's generation must not guess). ``mesh_heal`` records
        (graft-heal: a live reshard/re-expansion journaled ahead of its
        application) follow the identical discipline on their own
        monotonic ``heal_gen``."""

        def _keep(b) -> bool:
            if b.kind == "params_swap":
                return (through_params_gen is None
                        or b.meta.get("generation", 0) > through_params_gen)
            if b.kind == "mesh_heal":
                return (through_heal_gen is None
                        or b.meta.get("heal_gen", 0) > through_heal_gen)
            return b.seq_hi > through_seq

        batches, _ = self.read()
        keep = [b for b in batches if _keep(b)]
        tmp = self.wal_path + ".tmp"
        with open(tmp, "wb") as f:
            for b in keep:
                payload = pickle.dumps(
                    {"kind": b.kind, "seq_lo": b.seq_lo, "seq_hi": b.seq_hi,
                     "recs": b.recs, "meta": b.meta},
                    protocol=pickle.HIGHEST_PROTOCOL)
                # one fsync for the whole rewrite (below), not per frame
                _write_frame(f, payload, sync=False)
            f.flush()
            os.fsync(f.fileno())
        with self._io_lock:
            # appends that landed since read() are re-appended atomically:
            # re-read the live WAL tail not present in `keep`
            seen = {(b.kind, b.seq_lo, b.seq_hi, len(b.recs))
                    for b in batches}
            self._wal_f.flush()
            tail, _, _ = _read_frames(self.wal_path)
            with open(tmp, "ab") as f:
                for raw in tail:
                    d = pickle.loads(raw)
                    key = (d["kind"], d["seq_lo"], d["seq_hi"],
                           len(d["recs"]))
                    if key in seen:
                        continue
                    _write_frame(f, raw, sync=False)
                f.flush()
                os.fsync(f.fileno())
            self._wal_f.close()
            os.replace(tmp, self.wal_path)
            self._wal_f = open(self.wal_path, "ab")

    # -- snapshots ---------------------------------------------------------

    def write_snapshot(self, state: dict) -> int:
        """Atomic snapshot write: temp file + fsync + rename. A crash at
        any point (the fault harness injects one mid-payload) leaves the
        previous snapshot intact and a stale ``.tmp`` that the next write
        overwrites."""
        payload = pickle.dumps(state, protocol=pickle.HIGHEST_PROTOCOL)
        tmp = self.snap_path + ".tmp"
        with open(tmp, "wb") as f:
            _write_frame(f, payload, self.fault_hook, stage="snapshot_write")
        os.replace(tmp, self.snap_path)
        return _FRAME.size + len(payload)

    def load_snapshot(self) -> "dict | None":
        """Last durable snapshot, or None (absent or checksum-corrupt —
        a corrupt snapshot is unusable, never partially trusted)."""
        payloads, _valid, torn = _read_frames(self.snap_path)
        if torn or not payloads:
            if torn:
                log.warning("snapshot_corrupt_ignored", path=self.snap_path)
            return None
        try:
            return pickle.loads(payloads[0])
        except (pickle.UnpicklingError, EOFError, ValueError,
                AttributeError) as exc:
            log.warning("snapshot_unreadable", error=str(exc))
            return None

    def close(self) -> None:
        self._wal_f.close()
