"""GNN trainer — labeled episodes from the simulator, eval vs the oracle.

The reference has no trainable model (SURVEY.md §2.4: no model anywhere);
this is the framework's own addition on top of capability parity: the
KGroot-style GNN scorer (rca/gnn.py) trained on fault-injection episodes
whose labels are the scenarios' expected diagnosis rules — the same signal
the deterministic ruleset encodes, so eval accuracy is directly comparable
to the rules oracle.

Usage (also ``python -m kubernetes_aiops_evidence_graph_tpu.rca.train``):

    from kubernetes_aiops_evidence_graph_tpu.rca.train import train
    result = train(episodes=8, steps=200)   # -> params, metrics history
"""
from __future__ import annotations

import argparse
import json
import sys
from typing import Sequence

import numpy as np

import jax

from .ruleset import RULE_INDEX
from . import gnn


# Rule pairs the round-4 holdout showed the GNN (and at one incident even
# the oracle) confusing under evidence interference on small dense clusters
# (artifacts/gnn_residue.json: every miss was episode 125, a 96-pod/8-
# incident world). Dense episodes co-locate these in the same namespace so
# training sees exactly the overlap that caused the residue.
_CONFUSABLE_PAIRS = (          # scenario names (keyed for inject())
    ("oom", "crashloop"),               # oom_killed vs crashloop_no_change
    ("oom_pressure", "crashloop_deploy"),  # oom_high_memory vs recent_deploy
    ("probe_failure", "network"),       # readiness vs network_error
    ("config_error", "node_pressure"),  # config_error vs node_failure
    ("imagepull", "hpa_maxed"),
)


class _NullScorer:
    """stream_step sink when an episode needs store+cluster churn but no
    resident device state (training-data generation)."""

    def __getattr__(self, name):
        return lambda *a, **k: None


def _touches_protected(cluster, ev, deps: set, svcs: set) -> bool:
    """Would this churn event mutate state an injected incident's label
    depends on? Incident arrival/closure are always out (the label set
    must stay fixed); otherwise protection follows the event's target."""
    if ev.kind in ("incident_arrival", "incident_close"):
        return True
    key = f"{ev.namespace}/{ev.name}"
    if ev.kind == "rollout":
        return key in deps
    if ev.kind == "metric_drift":
        return key in svcs
    if ev.kind == "pod_create":
        return f"{ev.namespace}/{ev.payload['deployment']}" in deps
    p = cluster.pods.get(key)
    return p is not None and f"{p.namespace}/{p.deployment}" in deps


def make_episode(num_pods: int, num_incidents: int, seed: int,
                 churn: int = 0, dense: bool = False, unknowns: int = 0,
                 return_snapshot: bool = False) -> dict:
    """One labeled training episode: a fresh simulated cluster with
    ``num_incidents`` injected scenarios → snapshot batch + labels.

    ``churn`` applies that many background churn events (the streaming
    event mix) AFTER the last ingest, skipping anything that would touch
    an injected incident's deployment/service. After-ingest matters:
    interleaved churn leaks into later incidents' namespace-wide event /
    deploy-diff evidence (measured: oracle-label agreement dropped to
    38/48), whereas post-ingest churn shifts only the GNN's
    message-passing neighborhood — mid-stream cluster state at SCORING
    time — while the rule-visible evidence stays frozen, so labels stay
    derivable (VERDICT r4 item 4). ``dense=True`` targets adjacent deployments (stride 1 over
    the sorted keys — same-namespace runs) and orders scenarios as the
    confusable pairs above, maximizing evidence interference between
    incidents. ``unknowns`` additionally opens that many NO-FAULT
    incidents (alerts over healthy deployments: AFFECTS edges to healthy
    pods, nothing injected) labeled with the unknown class — without
    them the model never sees a negative example and confidently
    diagnoses healthy evidence (measured: 0.86-confidence oom on one
    healthy pod, where the rules engine abstains).
    ``return_snapshot=True`` adds the GraphSnapshot under ``"snapshot"``
    (oracle cross-checks; batch consumers ignore it)."""
    from ..collectors import collect_all, default_collectors
    from ..config import load_settings
    from ..graph import GraphBuilder, build_snapshot
    from ..graph.topology_sync import sync_topology
    from ..simulator import SCENARIOS, generate_cluster, inject
    from ..simulator.stream import churn_events, stream_step

    settings = load_settings(
        node_bucket_sizes=(256, 512, 1024, 4096),
        edge_bucket_sizes=(1024, 4096, 16384),
        incident_bucket_sizes=(8, 32),
    )
    cluster = generate_cluster(num_pods=num_pods, seed=seed)
    rng = np.random.default_rng(seed)
    deploy_keys = sorted(cluster.deployments)
    if dense:
        flat = [n for pair in _CONFUSABLE_PAIRS for n in pair]
        names = flat[seed % len(flat):] + flat[:seed % len(flat)]
    else:
        names = sorted(SCENARIOS)
    builder = GraphBuilder()
    sync_topology(cluster, builder.store)
    sink = _NullScorer()
    protected_deps: set[str] = set()
    protected_svcs: set[str] = set()
    labels = []
    stride = 1 if dense else 5
    for i in range(num_incidents):
        name = names[(seed + i) % len(names)] if not dense \
            else names[i % len(names)]
        target = deploy_keys[(i * stride) % len(deploy_keys)]
        inc = inject(cluster, name, target, rng)
        protected_deps.add(target)
        d = cluster.deployments.get(target)
        if d is not None:
            protected_svcs.add(f"{d.namespace}/{d.service}")
        builder.ingest(inc, collect_all(inc, default_collectors(cluster, settings),
                                        parallel=False))
        labels.append(RULE_INDEX[SCENARIOS[name].expected_rule])
    # no-fault incidents may only target deployments NO fault touched —
    # an index collision would attach genuinely faulty pods to an
    # "unknown"-labeled incident, poisoning the abstention class
    # (code-review r5: the arithmetic pick collided for 10% of episodes)
    faulted = {(i * stride) % len(deploy_keys) for i in range(num_incidents)}
    clean_idx = [j for j in range(len(deploy_keys)) if j not in faulted]
    for u in range(min(unknowns, len(clean_idx))):
        # a "false alarm": incident over a deployment nothing was injected
        # into — evidence exists (its healthy pods) but supports no rule
        from ..graph import ids
        from ..models import GraphEntity, GraphRelation
        target = deploy_keys[clean_idx[(u * 7 + 3) % len(clean_idx)]]
        ns, dname = target.split("/", 1)
        d = cluster.deployments[target]
        inc_nid = f"incident:unknown-{seed}-{u}"
        builder.store.upsert_entities([GraphEntity(
            id=inc_nid, type="Incident",
            properties={"severity": "low", "service": dname,
                        "namespace": ns})])
        pods = cluster.list_pods(ns, d.service)[:4]
        builder.store.upsert_relations([
            GraphRelation(source_id=inc_nid,
                          target_id=ids.pod_id(p.namespace, p.name),
                          relation_type="AFFECTS")
            for p in pods])
        labels.append(gnn.NUM_CLASSES - 1)
    if churn:
        applied = 0
        # oversample: some events are vetoed by protection
        for ev in churn_events(cluster, churn * 4, seed=seed * 1009 + 1):
            if applied >= churn:
                break
            if _touches_protected(cluster, ev, protected_deps,
                                  protected_svcs):
                continue
            stream_step(cluster, builder.store, sink, ev)
            applied += 1
    snap = build_snapshot(builder.store, settings, now_s=cluster.now.timestamp())
    batch = gnn.snapshot_batch(snap, np.asarray(labels, dtype=np.int32))
    if return_snapshot:
        batch["snapshot"] = snap
    return batch


def make_dataset(episodes: int, num_pods: int | Sequence[int] = 96,
                 num_incidents: int = 6, seed: int = 0, churn: int = 0,
                 dense: bool = False, unknowns: int = 0,
                 return_snapshot: bool = False) -> list[dict]:
    """``num_pods`` may be a sequence of cluster sizes, cycled per episode
    — the product-scale evaluation trains across 96→2k-pod clusters so the
    model sees every topology bucket, not one toy size. ``churn``/``dense``/
    ``return_snapshot`` pass through to make_episode."""
    sizes = ([num_pods] if isinstance(num_pods, int) else list(num_pods))
    return [make_episode(sizes[e % len(sizes)], num_incidents, seed + e,
                         churn=churn, dense=dense, unknowns=unknowns,
                         return_snapshot=return_snapshot)
            for e in range(episodes)]


def _predictions(params: gnn.Params, batches: Sequence[dict]
                 ) -> tuple[np.ndarray, np.ndarray]:
    """(labels, predictions) over the labeled incidents of ``batches``."""
    # forward_batch picks the relation-bucketed kernel for bucketed
    # layouts (per-slice sorted fast path) and the reference elsewhere
    y_true, y_pred = [], []
    for b in batches:
        # exactly ONE explicit host transfer per batch: everything
        # downstream (argmax, masking, the confusion matrix's .tolist())
        # is host numpy, so the whole eval path is clean under the
        # transfer-guard fixture (tests/test_graft_audit.py)
        logits = jax.device_get(gnn.forward_batch(params, b))
        pred = logits.argmax(axis=-1)
        mask = np.asarray(b["label_mask"]) > 0
        y_true.append(np.asarray(b["labels"])[mask])
        y_pred.append(pred[mask])
    if not y_true:
        return np.zeros(0, np.int32), np.zeros(0, np.int32)
    return np.concatenate(y_true), np.concatenate(y_pred)


def evaluate(params: gnn.Params, batches: Sequence[dict]) -> float:
    """Top-1 accuracy over the labeled (masked) incidents of ``batches``."""
    y, p = _predictions(params, batches)
    return float((y == p).sum()) / max(len(y), 1)


def confusion(params: gnn.Params, batches: Sequence[dict]) -> dict:
    """Per-rule confusion over ``batches`` (VERDICT r3 item 5).

    Returns {"matrix": [C+1][C+1] counts (row = true rule, col = predicted,
    last index = unknown), "per_rule": {rule_id: {support, correct,
    recall, precision}}, "accuracy": float, "incidents": int}."""
    from .ruleset import NUM_RULES, RULES

    y, p = _predictions(params, batches)
    c = NUM_RULES + 1
    mat = np.zeros((c, c), np.int64)
    np.add.at(mat, (y, p), 1)
    names = [r.id for r in RULES] + ["unknown"]
    per_rule = {}
    for i, name in enumerate(names):
        support = int(mat[i].sum())
        predicted = int(mat[:, i].sum())
        correct = int(mat[i, i])
        per_rule[name] = {
            "support": support,
            "correct": correct,
            "recall": correct / support if support else None,
            "precision": correct / predicted if predicted else None,
        }
    return {"matrix": mat.tolist(), "classes": names,
            "per_rule": per_rule,
            "accuracy": float((y == p).sum()) / max(len(y), 1),
            "incidents": int(len(y))}


def train(episodes: int = 8, steps: int = 200,
          num_pods: int | Sequence[int] = 96,
          num_incidents: int = 6, hidden: int = 64, layers: int = 3,
          lr: float = 3e-3, seed: int = 0, eval_holdout: int = 2,
          augment_dense: int = 0, augment_churn: int = 0,
          augment_small: int = 0, weight_decay: float = 0.0,
          with_confusion: bool = False, verbose: bool = False) -> dict:
    """Train on simulator episodes; returns params + metric history.

    The last ``eval_holdout`` episodes are never trained on. The
    product-scale evaluation recorded in BASELINE.md is
    ``python -m ...rca.train --episodes 130 --pods 96,256,512,1024,2048
    --incidents 8 --steps 2000 --holdout 30 --confusion`` — 1,040
    incidents, 240 held out, class-balanced over all 10 scenarios.

    ``augment_dense``/``augment_churn`` append that many interference /
    churned-mid-stream episodes (small dense clusters; see make_episode)
    to the TRAIN set only — the holdout stays the plain last
    ``eval_holdout`` episodes so accuracy is comparable across rounds.
    """
    import optax

    if episodes <= eval_holdout:
        raise ValueError(
            f"episodes ({episodes}) must exceed eval_holdout ({eval_holdout})")
    # snapshots ride along when the confusion/crosscheck eval will need
    # them — snapshot_batch shares the underlying arrays, so this is
    # cheap, and it saves crosscheck_holdout regenerating every holdout
    # episode from scratch (code-review r5)
    data = make_dataset(episodes, num_pods, num_incidents, seed,
                        return_snapshot=with_confusion)
    holdout = data[len(data) - eval_holdout:] if eval_holdout else []
    train_set = data[:len(data) - eval_holdout] if eval_holdout else data
    if augment_dense:
        # disjoint seed block; small clusters = maximal evidence overlap
        train_set = train_set + make_dataset(
            augment_dense, [96, 128], num_incidents, seed=seed + 50000,
            dense=True)
    if augment_churn:
        train_set = train_set + make_dataset(
            augment_churn, [96, 256, 512], num_incidents,
            seed=seed + 70000, churn=40 * max(num_incidents, 1))
    if augment_small:
        # plain small worlds: natural (stride-5) interference at the scale
        # where every round-4 holdout miss lived (96-pod episode 125);
        # each also carries two no-fault incidents so the unknown class
        # has training support
        train_set = train_set + make_dataset(
            augment_small, [96, 128], num_incidents, seed=seed + 90000,
            unknowns=2)

    # the jitted train step takes the batch dict as a pytree: the holdout
    # keeps its snapshots (crosscheck_holdout needs them), but TRAIN
    # batches must carry neither the snapshot nor the rel_offsets tuple
    # (its ints would trace) — offsets split out as the step's STATIC arg,
    # training through the relation-bucketed kernel. The per-relation
    # capacity ladder keeps the distinct-offsets (= compile) count small.
    train_offsets = [tuple(b.get("rel_offsets") or ()) or None
                     for b in train_set]
    train_set = [{k: v for k, v in b.items()
                  if k not in ("snapshot", "rel_offsets")}
                 for b in train_set]

    params = gnn.init_params(jax.random.PRNGKey(seed), hidden=hidden, layers=layers)
    tx = optax.adamw(lr, weight_decay=weight_decay) if weight_decay \
        else optax.adam(lr)
    opt_state = tx.init(params)
    step = gnn.make_train_step(tx)

    history = []
    for s in range(steps):
        i = s % len(train_set)
        batch = train_set[i]
        params, opt_state, loss = step(
            params, opt_state, batch, rel_offsets=train_offsets[i],
            slices_sorted=train_offsets[i] is not None)
        if s % max(steps // 10, 1) == 0 or s == steps - 1:
            history.append({"step": s, "loss": float(loss)})
            if verbose:
                print(f"step {s:5d} loss {float(loss):.4f}", file=sys.stderr)

    # one holdout forward pass serves both accuracy and the matrix
    holdout_cm = confusion(params, holdout) if holdout else None
    crosscheck = crosscheck_holdout(params, holdout) \
        if with_confusion and holdout else None
    metrics = {
        "train_accuracy": evaluate(params, train_set),
        "holdout_accuracy": holdout_cm["accuracy"] if holdout_cm else None,
        "train_incidents": sum(int(np.asarray(b["label_mask"]).sum())
                               for b in train_set),
        "holdout_incidents": sum(int(np.asarray(b["label_mask"]).sum())
                                 for b in holdout),
        "final_loss": history[-1]["loss"],
        "history": history,
    }
    if with_confusion and holdout_cm:
        metrics["holdout_confusion"] = holdout_cm
    if crosscheck is not None:
        metrics["holdout_crosscheck"] = crosscheck
    return {"params": params, "metrics": metrics,
            "config": {"hidden": hidden, "layers": layers}}


def crosscheck_holdout(params: gnn.Params,
                       holdout: Sequence[dict]) -> dict:
    """Characterize every holdout miss against the rules oracle on the
    SAME snapshot (VERDICT r4 item 4). A miss is ambiguous by
    construction when the scenario label is not recoverable from the
    graph at all, in either of two measurable ways:

    * the oracle is also wrong on that incident (its rule-visible
      evidence no longer derives the label), or
    * the incident has an indistinguishable TWIN — another incident in
      the same episode, different label, IDENTICAL oracle condition and
      score vectors. Small worlds produce these: two alerts on the same
      service collect the same pods/events after both faults landed, so
      the merged evidence supports both diagnoses equally (measured in
      round 5: every remaining holdout miss is half of such a twin pair
      — rows (2,6) and (4,0) of episode 125 have bit-identical score
      vectors). A deterministic scorer maps each signature to ONE label,
      so within a group of signature-identical incidents it can be right
      at most max-label-multiplicity times; ceiling_accuracy sums that
      per signature group (groups of any size, any label mix — not just
      twin PAIRS) over the holdout.

    clean_accuracy = accuracy over incidents that are neither
    oracle-underivable nor twins."""
    from collections import Counter

    from . import get_backend
    from .ruleset import RULES

    rule_ids = [r.id for r in RULES]
    backend = get_backend("tpu")
    misses, total, correct, ambiguous = [], 0, 0, 0
    clean_total = clean_correct = 0
    twin_flagged = 0
    achievable = 0
    for e, b in enumerate(holdout):
        if "snapshot" not in b:
            raise ValueError(
                "crosscheck_holdout needs batches built with "
                "return_snapshot=True (the oracle scores the snapshot)")
        logits = jax.device_get(gnn.forward_batch(params, b))
        pred = logits.argmax(-1)
        raw = backend.score_snapshot(b["snapshot"])
        oracle = np.asarray(raw["top_rule_index"])
        sig_scores = np.asarray(raw["scores"])
        sig_conds = np.asarray(raw["conditions"])
        mask = np.asarray(b["label_mask"]) > 0
        y = np.asarray(b["labels"])
        rows = np.nonzero(mask)[0]
        # indistinguishable-twin map: identical oracle signature, any
        # differently-labeled partner
        sig = {int(i): (sig_conds[i].tobytes(), sig_scores[i].tobytes())
               for i in rows}
        twin = {int(i): any(sig[int(j)] == sig[int(i)] and y[j] != y[i]
                            for j in rows if j != i)
                for i in rows}
        twin_flagged += sum(twin.values())
        # achievable ceiling: group by signature; a deterministic scorer
        # predicts ONE label per signature, so per group it can be right
        # at most max-label-multiplicity times (handles 3+-member groups
        # and >2 distinct labels, which the old pairs-only `// 2`
        # correction under/over-counted — ADVICE r5)
        groups: dict = {}
        for i in rows:
            groups.setdefault(sig[int(i)], Counter())[int(y[i])] += 1
        achievable += sum(max(c.values()) for c in groups.values())
        for i in rows:
            total += 1
            oracle_right = oracle[i] == y[i]
            is_clean = oracle_right and not twin[int(i)]
            if is_clean:
                clean_total += 1
            if pred[i] == y[i]:
                correct += 1
                clean_correct += int(is_clean)
                continue
            amb = (not oracle_right) or twin[int(i)]
            ambiguous += int(amb)
            misses.append({
                "holdout_index": int(e), "incident_row": int(i),
                "true_rule": rule_ids[y[i]],
                "gnn_pred": rule_ids[pred[i]] if pred[i] < len(rule_ids)
                else "unknown",
                "oracle_pred": rule_ids[oracle[i]]
                if 0 <= oracle[i] < len(rule_ids) else "unknown",
                "oracle_right": bool(oracle_right),
                "indistinguishable_twin": bool(twin[int(i)]),
                "ambiguous_by_construction": bool(amb),
            })
    ceiling = achievable / max(total, 1)
    return {
        "holdout_incidents": total,
        "accuracy": correct / max(total, 1),
        "misses": misses,
        "ambiguous_misses": ambiguous,
        "twin_incidents": twin_flagged,
        "ceiling_accuracy": ceiling,
        "clean_incidents": clean_total,
        "clean_accuracy": clean_correct / max(clean_total, 1),
    }


# -- checkpointing (orbax; SURVEY.md §5 checkpoint/resume) -----------------

def save_checkpoint(path: str, params: gnn.Params, config: dict) -> None:
    import orbax.checkpoint as ocp
    import os
    ckptr = ocp.PyTreeCheckpointer()
    ckptr.save(os.path.abspath(path), {"params": params, "config": config},
               force=True)  # allow overwriting a previous run's checkpoint


def load_checkpoint(path: str) -> dict:
    """Restore arrays as plain numpy so a checkpoint written on one
    platform/topology (e.g. CPU trainer) loads anywhere (e.g. TPU server)."""
    import orbax.checkpoint as ocp
    import os
    ckptr = ocp.PyTreeCheckpointer()
    path = os.path.abspath(path)
    meta = ckptr.metadata(path)
    tree = getattr(getattr(meta, "item_metadata", meta), "tree", None)
    if tree is None:  # older orbax: metadata() returns the tree directly
        tree = meta
    restore_args = jax.tree_util.tree_map(
        lambda _: ocp.RestoreArgs(restore_type=np.ndarray), tree)
    return ckptr.restore(path, restore_args=restore_args)


def main(argv: list[str] | None = None) -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--episodes", type=int, default=8)
    ap.add_argument("--steps", type=int, default=200)
    ap.add_argument("--pods", default="96",
                    help="cluster size, or comma list cycled per episode "
                         "(e.g. 96,256,512,1024,2048)")
    ap.add_argument("--incidents", type=int, default=6)
    ap.add_argument("--hidden", type=int, default=64)
    ap.add_argument("--layers", type=int, default=3)
    ap.add_argument("--lr", type=float, default=3e-3)
    ap.add_argument("--seed", type=int, default=0)
    ap.add_argument("--holdout", type=int, default=2)
    ap.add_argument("--augment-dense", type=int, default=0,
                    help="extra interference episodes (train set only)")
    ap.add_argument("--augment-churn", type=int, default=0,
                    help="extra churned mid-stream episodes (train set only)")
    ap.add_argument("--augment-small", type=int, default=0,
                    help="extra plain 96/128-pod episodes (train set only)")
    ap.add_argument("--weight-decay", type=float, default=0.0)
    ap.add_argument("--confusion", action="store_true",
                    help="include the per-rule holdout confusion matrix")
    ap.add_argument("--checkpoint", default="", help="save trained params here")
    args = ap.parse_args(argv)
    pods: int | list[int]
    pods = ([int(x) for x in args.pods.split(",")]
            if "," in str(args.pods) else int(args.pods))
    out = train(episodes=args.episodes, steps=args.steps, num_pods=pods,
                num_incidents=args.incidents, hidden=args.hidden,
                layers=args.layers, lr=args.lr, seed=args.seed,
                eval_holdout=args.holdout,
                augment_dense=args.augment_dense,
                augment_churn=args.augment_churn,
                augment_small=args.augment_small,
                weight_decay=args.weight_decay,
                with_confusion=args.confusion, verbose=True)
    if args.checkpoint:
        save_checkpoint(args.checkpoint, out["params"], out["config"])
    print(json.dumps(out["metrics"]))
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
