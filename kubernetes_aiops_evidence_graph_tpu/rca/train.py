"""GNN trainer — labeled episodes from the simulator, eval vs the oracle.

The reference has no trainable model (SURVEY.md §2.4: no model anywhere);
this is the framework's own addition on top of capability parity: the
KGroot-style GNN scorer (rca/gnn.py) trained on fault-injection episodes
whose labels are the scenarios' expected diagnosis rules — the same signal
the deterministic ruleset encodes, so eval accuracy is directly comparable
to the rules oracle.

Usage (also ``python -m kubernetes_aiops_evidence_graph_tpu.rca.train``):

    from kubernetes_aiops_evidence_graph_tpu.rca.train import train
    result = train(episodes=8, steps=200)   # -> params, metrics history
"""
from __future__ import annotations

import argparse
import json
import sys
from typing import Sequence

import numpy as np

import jax

from .ruleset import RULE_INDEX
from . import gnn


def make_episode(num_pods: int, num_incidents: int, seed: int) -> dict:
    """One labeled training episode: a fresh simulated cluster with
    ``num_incidents`` injected scenarios → snapshot batch + labels."""
    from ..collectors import collect_all, default_collectors
    from ..config import load_settings
    from ..graph import GraphBuilder, build_snapshot
    from ..graph.topology_sync import sync_topology
    from ..simulator import SCENARIOS, generate_cluster, inject

    settings = load_settings(
        node_bucket_sizes=(256, 512, 1024, 4096),
        edge_bucket_sizes=(1024, 4096, 16384),
        incident_bucket_sizes=(8, 32),
    )
    cluster = generate_cluster(num_pods=num_pods, seed=seed)
    rng = np.random.default_rng(seed)
    deploy_keys = sorted(cluster.deployments)
    names = sorted(SCENARIOS)
    builder = GraphBuilder()
    sync_topology(cluster, builder.store)
    labels = []
    for i in range(num_incidents):
        name = names[(seed + i) % len(names)]
        inc = inject(cluster, name, deploy_keys[(i * 5) % len(deploy_keys)], rng)
        builder.ingest(inc, collect_all(inc, default_collectors(cluster, settings),
                                        parallel=False))
        labels.append(RULE_INDEX[SCENARIOS[name].expected_rule])
    snap = build_snapshot(builder.store, settings, now_s=cluster.now.timestamp())
    return gnn.snapshot_batch(snap, np.asarray(labels, dtype=np.int32))


def make_dataset(episodes: int, num_pods: int | Sequence[int] = 96,
                 num_incidents: int = 6, seed: int = 0) -> list[dict]:
    """``num_pods`` may be a sequence of cluster sizes, cycled per episode
    — the product-scale evaluation trains across 96→2k-pod clusters so the
    model sees every topology bucket, not one toy size."""
    sizes = ([num_pods] if isinstance(num_pods, int) else list(num_pods))
    return [make_episode(sizes[e % len(sizes)], num_incidents, seed + e)
            for e in range(episodes)]


def _predictions(params: gnn.Params, batches: Sequence[dict]
                 ) -> tuple[np.ndarray, np.ndarray]:
    """(labels, predictions) over the labeled incidents of ``batches``."""
    fwd = jax.jit(gnn.forward)   # one wrapper: compile at most once per shape
    y_true, y_pred = [], []
    for b in batches:
        logits = fwd(
            params, b["features"], b["node_kind"], b["node_mask"],
            b["edge_src"], b["edge_dst"], b["edge_mask"], b["incident_nodes"])
        pred = np.asarray(logits.argmax(axis=-1))
        mask = np.asarray(b["label_mask"]) > 0
        y_true.append(np.asarray(b["labels"])[mask])
        y_pred.append(pred[mask])
    if not y_true:
        return np.zeros(0, np.int32), np.zeros(0, np.int32)
    return np.concatenate(y_true), np.concatenate(y_pred)


def evaluate(params: gnn.Params, batches: Sequence[dict]) -> float:
    """Top-1 accuracy over the labeled (masked) incidents of ``batches``."""
    y, p = _predictions(params, batches)
    return float((y == p).sum()) / max(len(y), 1)


def confusion(params: gnn.Params, batches: Sequence[dict]) -> dict:
    """Per-rule confusion over ``batches`` (VERDICT r3 item 5).

    Returns {"matrix": [C+1][C+1] counts (row = true rule, col = predicted,
    last index = unknown), "per_rule": {rule_id: {support, correct,
    recall, precision}}, "accuracy": float, "incidents": int}."""
    from .ruleset import NUM_RULES, RULES

    y, p = _predictions(params, batches)
    c = NUM_RULES + 1
    mat = np.zeros((c, c), np.int64)
    np.add.at(mat, (y, p), 1)
    names = [r.id for r in RULES] + ["unknown"]
    per_rule = {}
    for i, name in enumerate(names):
        support = int(mat[i].sum())
        predicted = int(mat[:, i].sum())
        correct = int(mat[i, i])
        per_rule[name] = {
            "support": support,
            "correct": correct,
            "recall": correct / support if support else None,
            "precision": correct / predicted if predicted else None,
        }
    return {"matrix": mat.tolist(), "classes": names,
            "per_rule": per_rule,
            "accuracy": float((y == p).sum()) / max(len(y), 1),
            "incidents": int(len(y))}


def train(episodes: int = 8, steps: int = 200,
          num_pods: int | Sequence[int] = 96,
          num_incidents: int = 6, hidden: int = 64, layers: int = 3,
          lr: float = 3e-3, seed: int = 0, eval_holdout: int = 2,
          with_confusion: bool = False, verbose: bool = False) -> dict:
    """Train on simulator episodes; returns params + metric history.

    The last ``eval_holdout`` episodes are never trained on. The
    product-scale evaluation recorded in BASELINE.md is
    ``python -m ...rca.train --episodes 130 --pods 96,256,512,1024,2048
    --incidents 8 --steps 2000 --holdout 30 --confusion`` — 1,040
    incidents, 240 held out, class-balanced over all 10 scenarios.
    """
    import optax

    if episodes <= eval_holdout:
        raise ValueError(
            f"episodes ({episodes}) must exceed eval_holdout ({eval_holdout})")
    data = make_dataset(episodes, num_pods, num_incidents, seed)
    holdout = data[len(data) - eval_holdout:] if eval_holdout else []
    train_set = data[:len(data) - eval_holdout] if eval_holdout else data

    params = gnn.init_params(jax.random.PRNGKey(seed), hidden=hidden, layers=layers)
    tx = optax.adam(lr)
    opt_state = tx.init(params)
    step = gnn.make_train_step(tx)

    history = []
    for s in range(steps):
        batch = train_set[s % len(train_set)]
        params, opt_state, loss = step(params, opt_state, batch)
        if s % max(steps // 10, 1) == 0 or s == steps - 1:
            history.append({"step": s, "loss": float(loss)})
            if verbose:
                print(f"step {s:5d} loss {float(loss):.4f}", file=sys.stderr)

    # one holdout forward pass serves both accuracy and the matrix
    holdout_cm = confusion(params, holdout) if holdout else None
    metrics = {
        "train_accuracy": evaluate(params, train_set),
        "holdout_accuracy": holdout_cm["accuracy"] if holdout_cm else None,
        "train_incidents": sum(int(np.asarray(b["label_mask"]).sum())
                               for b in train_set),
        "holdout_incidents": sum(int(np.asarray(b["label_mask"]).sum())
                                 for b in holdout),
        "final_loss": history[-1]["loss"],
        "history": history,
    }
    if with_confusion and holdout_cm:
        metrics["holdout_confusion"] = holdout_cm
    return {"params": params, "metrics": metrics,
            "config": {"hidden": hidden, "layers": layers}}


# -- checkpointing (orbax; SURVEY.md §5 checkpoint/resume) -----------------

def save_checkpoint(path: str, params: gnn.Params, config: dict) -> None:
    import orbax.checkpoint as ocp
    import os
    ckptr = ocp.PyTreeCheckpointer()
    ckptr.save(os.path.abspath(path), {"params": params, "config": config},
               force=True)  # allow overwriting a previous run's checkpoint


def load_checkpoint(path: str) -> dict:
    """Restore arrays as plain numpy so a checkpoint written on one
    platform/topology (e.g. CPU trainer) loads anywhere (e.g. TPU server)."""
    import orbax.checkpoint as ocp
    import os
    ckptr = ocp.PyTreeCheckpointer()
    path = os.path.abspath(path)
    meta = ckptr.metadata(path)
    tree = getattr(getattr(meta, "item_metadata", meta), "tree", None)
    if tree is None:  # older orbax: metadata() returns the tree directly
        tree = meta
    restore_args = jax.tree_util.tree_map(
        lambda _: ocp.RestoreArgs(restore_type=np.ndarray), tree)
    return ckptr.restore(path, restore_args=restore_args)


def main(argv: list[str] | None = None) -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--episodes", type=int, default=8)
    ap.add_argument("--steps", type=int, default=200)
    ap.add_argument("--pods", default="96",
                    help="cluster size, or comma list cycled per episode "
                         "(e.g. 96,256,512,1024,2048)")
    ap.add_argument("--incidents", type=int, default=6)
    ap.add_argument("--hidden", type=int, default=64)
    ap.add_argument("--layers", type=int, default=3)
    ap.add_argument("--lr", type=float, default=3e-3)
    ap.add_argument("--seed", type=int, default=0)
    ap.add_argument("--holdout", type=int, default=2)
    ap.add_argument("--confusion", action="store_true",
                    help="include the per-rule holdout confusion matrix")
    ap.add_argument("--checkpoint", default="", help="save trained params here")
    args = ap.parse_args(argv)
    pods: int | list[int]
    pods = ([int(x) for x in args.pods.split(",")]
            if "," in str(args.pods) else int(args.pods))
    out = train(episodes=args.episodes, steps=args.steps, num_pods=pods,
                num_incidents=args.incidents, hidden=args.hidden,
                layers=args.layers, lr=args.lr, seed=args.seed,
                eval_holdout=args.holdout, with_confusion=args.confusion,
                verbose=True)
    if args.checkpoint:
        save_checkpoint(args.checkpoint, out["params"], out["config"])
    print(json.dumps(out["metrics"]))
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
