"""Device-computed blast propagation — "which parts of the mesh feel this
incident".

Product surface for ops/propagate.py's two primitives (VERDICT r1 item 10:
they were bench/test-only). Seeds the incident node, bounds the blast set
with :func:`~..ops.propagate.k_hop_reach` (the apoc.path.subgraphAll
maxLevel analog, neo4j.py:169-201), and ranks nodes inside that set by
iterated label propagation — entities topologically closer to the incident
through denser paths score higher than the flat membership the reference's
Cypher traversal returns. Complements the arithmetic blast-radius formula
(remediation/orchestrator.py): that scores the proposed ACTION, this maps
the topological SPREAD.
"""
from __future__ import annotations

import weakref

import numpy as np

import jax
import jax.numpy as jnp

from ..config import Settings
from ..graph.snapshot import GraphSnapshot, build_snapshot
from ..graph.store import EvidenceGraphStore
from ..ops.propagate import k_hop_reach, propagate_labels

# snapshot cache keyed by (live) store + version: repeated API calls against
# an unchanged graph skip the O(N) tensorize + device upload. Weak keys mean
# entries die with their store — no unbounded growth across tests, and no
# id()-reuse aliasing serving a dead store's snapshot to a new one.
_CACHE: "weakref.WeakKeyDictionary[EvidenceGraphStore, tuple[int, Settings | None, GraphSnapshot]]" = \
    weakref.WeakKeyDictionary()


def _snapshot(store: EvidenceGraphStore, settings: Settings | None) -> GraphSnapshot:
    hit = _CACHE.get(store)
    if hit is not None and hit[0] == store.version and hit[1] is settings:
        return hit[2]
    snap = build_snapshot(store, settings)
    _CACHE[store] = (store.version, settings, snap)
    return snap


def blast_propagation(
    store: EvidenceGraphStore,
    incident_id: str,
    settings: Settings | None = None,
    hops: int = 3,
    iterations: int = 3,
    alpha: float = 0.5,
    top_k: int = 25,
) -> dict | None:
    """Propagated blast map for one incident; None if it isn't in the graph."""
    nid = incident_id if incident_id.startswith("incident:") \
        else f"incident:{incident_id}"
    snap = _snapshot(store, settings)
    if nid not in snap.node_ids:
        return None
    seed = snap.node_ids.index(nid)
    pn = snap.padded_nodes

    reach = k_hop_reach(
        jnp.asarray([seed], jnp.int32), jnp.asarray([1.0], jnp.float32),
        jnp.asarray(snap.edge_src), jnp.asarray(snap.edge_dst),
        jnp.asarray(snap.edge_mask), num_nodes=pn, hops=hops)[0]

    x = jnp.zeros((pn,), jnp.float32).at[seed].set(1.0)
    scores = propagate_labels(
        x, jnp.asarray(snap.edge_src), jnp.asarray(snap.edge_dst),
        jnp.asarray(snap.edge_mask), num_nodes=pn,
        iterations=iterations, alpha=alpha)

    # rank only nodes inside the k-hop blast set; drop pads and the seed.
    # ONE explicit fetch for both outputs (implicit np.asarray syncs are
    # a host-sync lint violation); np.array copies because we mutate
    # ranked[seed] below and device_get may return a read-only view.
    reach_masked = reach * jnp.asarray(snap.node_mask)
    ranked, reach_host = jax.device_get((scores * reach_masked, reach_masked))
    ranked = np.array(ranked)
    ranked[seed] = 0.0
    order = np.argsort(-ranked, kind="stable")
    blast = []
    for i in order[:top_k]:
        if ranked[i] <= 0.0:
            break
        node = store.get_node(snap.node_ids[i])
        blast.append({
            "id": snap.node_ids[i],
            "type": node["type"] if node else "?",
            "score": round(float(ranked[i]), 6),
        })
    n_reached = int(reach_host.sum()) - 1
    return {
        "incident": nid,
        "hops": hops,
        "iterations": iterations,
        "reached_nodes": max(n_reached, 0),
        "blast": blast,
    }
