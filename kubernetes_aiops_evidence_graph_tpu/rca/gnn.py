"""Learnable GNN RCA scorer — the framework's flagship model.

A KGroot-style RELATION-AWARE graph scorer (PAPERS.md: KGroot, GCN-based
RCA; R-GCN-style per-relation transforms) over the tensorized evidence
graph: node features + entity-kind embeddings, K rounds of segment-sum
message passing with a separate [H, H] transform per RelationKind, and an
incident-node readout to rule logits (NUM_RULES + 1 classes, last =
unknown). Relation awareness is what disentangles co-located incidents:
an incident node's OWN evidence arrives over AFFECTS edges while the
deployment/service commons arrive over OWNS/SELECTS/SCHEDULED_ON paths —
a relation-blind mean blends them, and measurably confuses incident pairs
sharing a deployment (round-4 holdout: every miss predicted its
deployment-mate's rule). The per-relation math is mapped as
transform-then-gather: R stacked MXU matmuls produce every relation's
transformed copy, each edge gathers its rel-specific source row, and
aggregation stays one [E, H] segment-sum (see _message_pass for the
measured 9.4x penalty of the scatter-bucket alternative).

Complements the deterministic ruleset backend with a trainable one
(HypothesisSource.GNN); simulator scenarios provide labeled training data.

Pure-JAX pytree parameters (no flax dependency in the hot path); the math
lives here device-agnostic, the multi-chip sharded training step lives in
``parallel/sharded_gnn.py``.
"""
from __future__ import annotations

from typing import Any

import jax
import jax.numpy as jnp

from ..graph.schema import DIM, EntityKind, RelationKind
from .ruleset import NUM_RULES

NUM_CLASSES = NUM_RULES + 1   # + unknown
NUM_KINDS = len(EntityKind)   # embedding rows track the schema
NUM_RELS = len(RelationKind)  # per-relation message transforms

Params = dict[str, Any]


def init_params(key: jax.Array, hidden: int = 64, layers: int = 3) -> Params:
    keys = jax.random.split(key, 3 + 2 * layers)
    scale = lambda fan_in: 1.0 / jnp.sqrt(fan_in)
    params: Params = {
        "embed_w": jax.random.normal(keys[0], (DIM, hidden)) * scale(DIM),
        "embed_b": jnp.zeros((hidden,)),
        "kind_emb": jax.random.normal(keys[1], (NUM_KINDS, hidden)) * 0.1,
        "head_w": jax.random.normal(keys[2], (hidden, NUM_CLASSES)) * scale(hidden),
        "head_b": jnp.zeros((NUM_CLASSES,)),
        "layers": [],
    }
    for i in range(layers):
        params["layers"].append({
            "w_self": jax.random.normal(keys[3 + 2 * i], (hidden, hidden)) * scale(hidden),
            "w_rel": jax.random.normal(
                keys[4 + 2 * i], (NUM_RELS, hidden, hidden)) * scale(hidden),
            "b": jnp.zeros((hidden,)),
        })
    return params


def rel_messages(h_table, w_rel, src_index, edge_rel, edge_mask):
    """[E, H] per-edge messages under the transform-then-gather mapping —
    THE one implementation of the relation-aware kernel (see
    _message_pass for why the scatter-bucket alternative lost 9.4x):
    every relation's transformed copy of ``h_table`` is computed densely
    (stacked MXU matmuls), then each edge gathers its rel-specific source
    row via the flattened index. Shared by the single-device layer and
    both sharded halo strategies (parallel/sharded_gnn.py), so the
    bit-identical-to-single-device invariant rests on one kernel."""
    rel = jnp.clip(edge_rel, 0, NUM_RELS - 1)
    hr = jnp.einsum("nh,rhk->nrk", h_table, w_rel)      # [N, R, H]
    flat = hr.reshape(h_table.shape[0] * NUM_RELS, h_table.shape[1])
    return flat[src_index * NUM_RELS + rel] * edge_mask[:, None]


def _message_pass(h, layer, edge_src, edge_dst, edge_rel, edge_mask,
                  inv_deg, sorted_by_dst: bool = False):
    """One relation-aware round, TPU-mapped as transform-THEN-gather: the
    per-relation transform is linear, so sum_e W_{rel_e} h_src ==
    sum_r W_r (sum_{e: rel_e=r} h_src). Computing all R transformed
    copies densely first ([N, R, H] einsum — R stacked matmuls on the
    MXU) lets each edge GATHER its source's rel-specific row (flattened
    1-D gather) and keeps the aggregation the ORIGINAL single [E, H]
    segment-sum. The alternative — scatter into per-(node, relation)
    buckets with a 2-D index — measured 9.4x slower on v5e-1 (291 ms vs
    31 ms at the 58k-node config): TPU scatters serialize, matmuls don't.
    Padded edges carry rel=-1: clipped to 0, but their mask already
    zeroes the message."""
    msg = rel_messages(h, layer["w_rel"], edge_src, edge_rel, edge_mask)
    agg = jax.ops.segment_sum(
        msg, edge_dst, num_segments=h.shape[0],
        indices_are_sorted=sorted_by_dst) * inv_deg[:, None]
    return jax.nn.relu(h @ layer["w_self"] + agg + layer["b"]) + h


def forward(
    params: Params,
    features: jax.Array,        # [N, DIM] f32
    node_kind: jax.Array,       # [N] i32
    node_mask: jax.Array,       # [N] f32
    edge_src: jax.Array,        # [E] i32
    edge_dst: jax.Array,        # [E] i32
    edge_rel: jax.Array,        # [E] i32 (RelationKind; -1 = padding)
    edge_mask: jax.Array,       # [E] f32
    incident_nodes: jax.Array,  # [B] i32
    *,
    sorted_by_dst: bool = False,
) -> jax.Array:
    """Logits [B, NUM_CLASSES] for each incident node.

    ``sorted_by_dst=True`` (STATIC — bind it via functools.partial before
    jitting) promises edge_dst is non-decreasing, letting every
    segment-sum take the sorted fast path (measured 1.9x on the v5e
    scatter). build_snapshot emits dst-sorted edges, so snapshot-based
    scoring can pass it; the streaming edge mirror is slot-ordered and
    must not."""
    deg = jax.ops.segment_sum(edge_mask, edge_dst,
                              num_segments=features.shape[0],
                              indices_are_sorted=sorted_by_dst)
    inv_deg = jnp.where(deg > 0, 1.0 / jnp.maximum(deg, 1.0), 0.0)
    h = jax.nn.relu(features @ params["embed_w"] + params["embed_b"]
                    + params["kind_emb"][node_kind])
    h = h * node_mask[:, None]
    for layer in params["layers"]:
        h = _message_pass(h, layer, edge_src, edge_dst, edge_rel,
                          edge_mask, inv_deg, sorted_by_dst=sorted_by_dst)
    return h[incident_nodes] @ params["head_w"] + params["head_b"]


def loss_fn(
    params: Params,
    features, node_kind, node_mask, edge_src, edge_dst, edge_rel,
    edge_mask, incident_nodes, labels, label_mask,
) -> jax.Array:
    """Masked mean cross-entropy over incident rows."""
    logits = forward(params, features, node_kind, node_mask,
                     edge_src, edge_dst, edge_rel, edge_mask, incident_nodes)
    logp = jax.nn.log_softmax(logits, axis=-1)
    nll = -jnp.take_along_axis(logp, labels[:, None], axis=1)[:, 0]
    return (nll * label_mask).sum() / jnp.maximum(label_mask.sum(), 1.0)


def make_train_step(tx):
    """Single-device train step (optax transform tx); the sharded variant is
    parallel.sharded_gnn.make_sharded_train_step."""

    @jax.jit
    def step(params, opt_state, batch):
        loss, grads = jax.value_and_grad(loss_fn)(
            params,
            batch["features"], batch["node_kind"], batch["node_mask"],
            batch["edge_src"], batch["edge_dst"], batch["edge_rel"],
            batch["edge_mask"],
            batch["incident_nodes"], batch["labels"], batch["label_mask"],
        )
        updates, opt_state = tx.update(grads, opt_state, params)
        params = jax.tree_util.tree_map(lambda p, u: p + u, params, updates)
        return params, opt_state, loss

    return step


def edges_sorted_by_dst(edge_dst) -> bool:
    """Host-side check of the sorted-segment-sum promise (one shared
    predicate — gnn_backend, device_metrics and the trainer all key the
    static ``sorted_by_dst`` flag off it)."""
    import numpy as np
    d = np.asarray(edge_dst)
    return bool((d[1:] >= d[:-1]).all())


def snapshot_batch(snapshot, labels=None) -> dict:
    """Pack a GraphSnapshot (+ optional int labels per incident) into the
    array batch consumed by forward/loss."""
    import numpy as np
    n_inc = snapshot.padded_incidents
    lab = np.full(n_inc, NUM_CLASSES - 1, dtype=np.int32)
    if labels is not None:
        lab[:len(labels)] = np.asarray(labels, dtype=np.int32)
    return {
        "features": snapshot.features,
        "node_kind": snapshot.node_kind,
        "node_mask": snapshot.node_mask,
        "edge_src": snapshot.edge_src,
        "edge_dst": snapshot.edge_dst,
        "edge_rel": snapshot.edge_rel,
        "edge_mask": snapshot.edge_mask,
        "incident_nodes": snapshot.incident_nodes,
        "labels": lab,
        "label_mask": snapshot.incident_mask,
    }
