"""Learnable GNN RCA scorer — the framework's flagship model.

A KGroot-style RELATION-AWARE graph scorer (PAPERS.md: KGroot, GCN-based
RCA; R-GCN-style per-relation transforms) over the tensorized evidence
graph: node features + entity-kind embeddings, K rounds of segment-sum
message passing with a separate [H, H] transform per RelationKind, and an
incident-node readout to rule logits (NUM_RULES + 1 classes, last =
unknown). Relation awareness is what disentangles co-located incidents:
an incident node's OWN evidence arrives over AFFECTS edges while the
deployment/service commons arrive over OWNS/SELECTS/SCHEDULED_ON paths —
a relation-blind mean blends them, and measurably confuses incident pairs
sharing a deployment (round-4 holdout: every miss predicted its
deployment-mate's rule). The per-relation aggregation is one [N, R, H]
scatter + one nrh,rhk einsum — dense MXU work, no sparse ops.

Complements the deterministic ruleset backend with a trainable one
(HypothesisSource.GNN); simulator scenarios provide labeled training data.

Pure-JAX pytree parameters (no flax dependency in the hot path); the math
lives here device-agnostic, the multi-chip sharded training step lives in
``parallel/sharded_gnn.py``.
"""
from __future__ import annotations

from typing import Any

import jax
import jax.numpy as jnp

from ..graph.schema import DIM, EntityKind, RelationKind
from .ruleset import NUM_RULES

NUM_CLASSES = NUM_RULES + 1   # + unknown
NUM_KINDS = len(EntityKind)   # embedding rows track the schema
NUM_RELS = len(RelationKind)  # per-relation message transforms

Params = dict[str, Any]


def init_params(key: jax.Array, hidden: int = 64, layers: int = 3) -> Params:
    keys = jax.random.split(key, 3 + 2 * layers)
    scale = lambda fan_in: 1.0 / jnp.sqrt(fan_in)
    params: Params = {
        "embed_w": jax.random.normal(keys[0], (DIM, hidden)) * scale(DIM),
        "embed_b": jnp.zeros((hidden,)),
        "kind_emb": jax.random.normal(keys[1], (NUM_KINDS, hidden)) * 0.1,
        "head_w": jax.random.normal(keys[2], (hidden, NUM_CLASSES)) * scale(hidden),
        "head_b": jnp.zeros((NUM_CLASSES,)),
        "layers": [],
    }
    for i in range(layers):
        params["layers"].append({
            "w_self": jax.random.normal(keys[3 + 2 * i], (hidden, hidden)) * scale(hidden),
            "w_rel": jax.random.normal(
                keys[4 + 2 * i], (NUM_RELS, hidden, hidden)) * scale(hidden),
            "b": jnp.zeros((hidden,)),
        })
    return params


def _message_pass(h, layer, edge_src, edge_dst, edge_rel, edge_mask, inv_deg):
    """One relation-aware round: messages segment-sum into per-(node,
    relation) buckets, then each relation's bucket goes through its own
    transform (one dense einsum — R stacked matmuls on the MXU). Padded
    edges carry rel=-1: clipped to 0, but their mask already zeroes the
    message."""
    msg = h[edge_src] * edge_mask[:, None]
    rel = jnp.clip(edge_rel, 0, NUM_RELS - 1)
    agg = jnp.zeros((h.shape[0], NUM_RELS, h.shape[1]), h.dtype
                    ).at[edge_dst, rel].add(msg) * inv_deg[:, None, None]
    mixed = jnp.einsum("nrh,rhk->nk", agg, layer["w_rel"])
    return jax.nn.relu(h @ layer["w_self"] + mixed + layer["b"]) + h


def forward(
    params: Params,
    features: jax.Array,        # [N, DIM] f32
    node_kind: jax.Array,       # [N] i32
    node_mask: jax.Array,       # [N] f32
    edge_src: jax.Array,        # [E] i32
    edge_dst: jax.Array,        # [E] i32
    edge_rel: jax.Array,        # [E] i32 (RelationKind; -1 = padding)
    edge_mask: jax.Array,       # [E] f32
    incident_nodes: jax.Array,  # [B] i32
) -> jax.Array:
    """Logits [B, NUM_CLASSES] for each incident node."""
    deg = jnp.zeros(features.shape[0], features.dtype).at[edge_dst].add(edge_mask)
    inv_deg = jnp.where(deg > 0, 1.0 / jnp.maximum(deg, 1.0), 0.0)
    h = jax.nn.relu(features @ params["embed_w"] + params["embed_b"]
                    + params["kind_emb"][node_kind])
    h = h * node_mask[:, None]
    for layer in params["layers"]:
        h = _message_pass(h, layer, edge_src, edge_dst, edge_rel,
                          edge_mask, inv_deg)
    return h[incident_nodes] @ params["head_w"] + params["head_b"]


def loss_fn(
    params: Params,
    features, node_kind, node_mask, edge_src, edge_dst, edge_rel,
    edge_mask, incident_nodes, labels, label_mask,
) -> jax.Array:
    """Masked mean cross-entropy over incident rows."""
    logits = forward(params, features, node_kind, node_mask,
                     edge_src, edge_dst, edge_rel, edge_mask, incident_nodes)
    logp = jax.nn.log_softmax(logits, axis=-1)
    nll = -jnp.take_along_axis(logp, labels[:, None], axis=1)[:, 0]
    return (nll * label_mask).sum() / jnp.maximum(label_mask.sum(), 1.0)


def make_train_step(tx):
    """Single-device train step (optax transform tx); the sharded variant is
    parallel.sharded_gnn.make_sharded_train_step."""

    @jax.jit
    def step(params, opt_state, batch):
        loss, grads = jax.value_and_grad(loss_fn)(
            params,
            batch["features"], batch["node_kind"], batch["node_mask"],
            batch["edge_src"], batch["edge_dst"], batch["edge_rel"],
            batch["edge_mask"],
            batch["incident_nodes"], batch["labels"], batch["label_mask"],
        )
        updates, opt_state = tx.update(grads, opt_state, params)
        params = jax.tree_util.tree_map(lambda p, u: p + u, params, updates)
        return params, opt_state, loss

    return step


def snapshot_batch(snapshot, labels=None) -> dict:
    """Pack a GraphSnapshot (+ optional int labels per incident) into the
    array batch consumed by forward/loss."""
    import numpy as np
    n_inc = snapshot.padded_incidents
    lab = np.full(n_inc, NUM_CLASSES - 1, dtype=np.int32)
    if labels is not None:
        lab[:len(labels)] = np.asarray(labels, dtype=np.int32)
    return {
        "features": snapshot.features,
        "node_kind": snapshot.node_kind,
        "node_mask": snapshot.node_mask,
        "edge_src": snapshot.edge_src,
        "edge_dst": snapshot.edge_dst,
        "edge_rel": snapshot.edge_rel,
        "edge_mask": snapshot.edge_mask,
        "incident_nodes": snapshot.incident_nodes,
        "labels": lab,
        "label_mask": snapshot.incident_mask,
    }
