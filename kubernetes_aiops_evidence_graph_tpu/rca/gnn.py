"""Learnable GNN RCA scorer — the framework's flagship model.

A KGroot-style RELATION-AWARE graph scorer (PAPERS.md: KGroot, GCN-based
RCA; R-GCN-style per-relation transforms) over the tensorized evidence
graph: node features + entity-kind embeddings, K rounds of segment-sum
message passing with a separate [H, H] transform per RelationKind, and an
incident-node readout to rule logits (NUM_RULES + 1 classes, last =
unknown). Relation awareness is what disentangles co-located incidents:
an incident node's OWN evidence arrives over AFFECTS edges while the
deployment/service commons arrive over OWNS/SELECTS/SCHEDULED_ON paths —
a relation-blind mean blends them, and measurably confuses incident pairs
sharing a deployment (round-4 holdout: every miss predicted its
deployment-mate's rule).

Three mappings of the per-relation math, selected by the snapshot layout
and two settings flags (settings.gnn_bucketed is the escape hatch back to
the reference; settings.gnn_pallas promotes serving to the Pallas tier):

* **Relation-bucketed (the hot path)** — build_snapshot lays edges out
  sorted by (rel, dst) with a STATIC per-relation offset table, so each
  relation is a contiguous edge slice: gather h[src] per slice
  ([E_r, H]), ONE [H, H] MXU matmul per relation, and per-slice
  dst-segment-sums into a single [N, H] accumulator
  (ops.gather_matmul_segment). Compute and HBM traffic scale with E, not
  N·R. This is NOT the scatter-bucket loser (see below): there are no
  2-D scatters anywhere — slices are static, scatters stay 1-D and
  per-slice dst-sorted. At the 50k-node/500-incident bench config this
  kills the reference's per-layer [N, R, H] materialization (151 MB
  written + re-read; 508 -> 365 MB/layer floor-model traffic), shrinks
  the row-addressed gather table 9.4x ([Pn*R, H] 151 MB -> [Pn, H]
  16 MB — small enough to live near the compute instead of streaming
  from HBM per row), and cuts the padded edge count 1.82x (524288 ->
  287488; gathers and scatters both walk padded rows, and TPU row ops
  serialize — they, not the MXU work, are what held the reference to
  7.8% of roofline). Optional bf16 compute path: matmul operands cast
  once before the gathers (half the per-row gather bytes), f32
  accumulation in the segment-sum. Measured numbers live in BENCH
  (bench.py reports reference vs bucketed vs bf16 on the same snapshot
  each run).
* **Pallas tier (serving, behind settings.gnn_pallas)** — the same
  relation-bucketed math as one tiled VMEM-resident kernel
  (ops/pallas_segment.py): the node table and the [N, H] accumulator stay
  in VMEM for the whole pass, edge tiles stream through with their
  relation id scalar-prefetched, each tile runs one MXU matmul and
  accumulates destination rows against VMEM instead of issuing per-edge
  HBM scatter-adds. BIT-identical to the bucketed kernel (exact-edge-order
  fold; interpret=True on CPU). Since graft-fuse the kernel carries a
  custom_vjp (transposed-layout Pallas backward), so gradients work on
  this tier too; the XLA bucketed kernel remains the parity oracle for
  both directions. The fused streaming tick (settings.gnn_fused_tick)
  additionally collapses the whole serving tick — delta scatter, message
  pass, scoring — into one Pallas kernel (ops/pallas_segment.py). BENCH
  config 3 carries the pallas-vs-XLA A/B record
  (gnn_forward_pallas_vs_xla) plus the fused-vs-composed record.
* **Transform-then-gather (reference)** — R stacked MXU matmuls produce
  every relation's transformed copy ([N, R, H] einsum), each edge
  gathers its rel-specific source row, aggregation is one [E, H]
  segment-sum. Kept as the parity oracle behind the
  settings.gnn_bucketed flag; round-5 BENCH measured it at 7.8% of the
  roofline floor (41.0 ms/forward, 49.6 GB/s achieved on a 635.8 GB/s
  part) — the gap this rewrite exists to close.
* the scatter-bucket alternative — scatter messages into per-(node,
  relation) buckets with a 2-D index — measured 9.4x SLOWER than the
  reference (291 ms vs 31 ms at the 58k-node config on v5e-1): TPU
  scatters serialize, matmuls don't. See _message_pass.

Complements the deterministic ruleset backend with a trainable one
(HypothesisSource.GNN); simulator scenarios provide labeled training data.

Pure-JAX pytree parameters (no flax dependency in the hot path); the math
lives here device-agnostic, the multi-chip sharded training step lives in
``parallel/sharded_gnn.py``.
"""
from __future__ import annotations

from functools import partial
from typing import Any

import jax
import jax.numpy as jnp

from ..graph.schema import DIM, EntityKind, RelationKind
from .ruleset import NUM_RULES

NUM_CLASSES = NUM_RULES + 1   # + unknown
NUM_KINDS = len(EntityKind)   # embedding rows track the schema
NUM_RELS = len(RelationKind)  # per-relation message transforms

Params = dict[str, Any]


def init_params(key: jax.Array, hidden: int = 64, layers: int = 3) -> Params:
    keys = jax.random.split(key, 3 + 2 * layers)
    scale = lambda fan_in: 1.0 / jnp.sqrt(fan_in)
    params: Params = {
        "embed_w": jax.random.normal(keys[0], (DIM, hidden)) * scale(DIM),
        "embed_b": jnp.zeros((hidden,)),
        "kind_emb": jax.random.normal(keys[1], (NUM_KINDS, hidden)) * 0.1,
        "head_w": jax.random.normal(keys[2], (hidden, NUM_CLASSES)) * scale(hidden),
        "head_b": jnp.zeros((NUM_CLASSES,)),
        "layers": [],
    }
    for i in range(layers):
        params["layers"].append({
            "w_self": jax.random.normal(keys[3 + 2 * i], (hidden, hidden)) * scale(hidden),
            "w_rel": jax.random.normal(
                keys[4 + 2 * i], (NUM_RELS, hidden, hidden)) * scale(hidden),
            "b": jnp.zeros((hidden,)),
        })
    return params


def rel_messages(h_table, w_rel, src_index, edge_rel, edge_mask):
    """[E, H] per-edge messages under the transform-then-gather mapping —
    the REFERENCE implementation of the relation-aware kernel (see
    _message_pass for why the scatter-bucket alternative lost 9.4x, and
    the module docstring for the relation-bucketed hot path that
    supersedes this one on bucketed layouts): every relation's
    transformed copy of ``h_table`` is computed densely (stacked MXU
    matmuls), then each edge gathers its rel-specific source row via the
    flattened index. Shared by the single-device reference layer and both
    sharded halo strategies' reference mode (parallel/sharded_gnn.py), so
    the bit-identical-to-single-device invariant of that mode rests on
    one kernel."""
    rel = jnp.clip(edge_rel, 0, NUM_RELS - 1)
    hr = jnp.einsum("nh,rhk->nrk", h_table, w_rel)      # [N, R, H]
    flat = hr.reshape(h_table.shape[0] * NUM_RELS, h_table.shape[1])
    return flat[src_index * NUM_RELS + rel] * edge_mask[:, None]


def _message_pass(h, layer, edge_src, edge_dst, edge_rel, edge_mask,
                  inv_deg, sorted_by_dst: bool = False):
    """One relation-aware round, TPU-mapped as transform-THEN-gather: the
    per-relation transform is linear, so sum_e W_{rel_e} h_src ==
    sum_r W_r (sum_{e: rel_e=r} h_src). Computing all R transformed
    copies densely first ([N, R, H] einsum — R stacked matmuls on the
    MXU) lets each edge GATHER its source's rel-specific row (flattened
    1-D gather) and keeps the aggregation the ORIGINAL single [E, H]
    segment-sum. The alternative — scatter into per-(node, relation)
    buckets with a 2-D index — measured 9.4x slower on v5e-1 (291 ms vs
    31 ms at the 58k-node config): TPU scatters serialize, matmuls don't.
    Padded edges carry rel=-1: clipped to 0, but their mask already
    zeroes the message."""
    msg = rel_messages(h, layer["w_rel"], edge_src, edge_rel, edge_mask)
    agg = jax.ops.segment_sum(
        msg, edge_dst, num_segments=h.shape[0],
        indices_are_sorted=sorted_by_dst) * inv_deg[:, None]
    return jax.nn.relu(h @ layer["w_self"] + agg + layer["b"]) + h


def _message_pass_bucketed(h, layer, edge_src, edge_dst, edge_mask,
                           rel_offsets, inv_deg, slices_sorted: bool,
                           compute_dtype, use_pallas: bool = False):
    """One relation-aware round over the relation-bucketed edge layout
    (module docstring): the fused gather → per-relation matmul →
    per-slice segment-sum helper replaces both the dense [N, R, H]
    transform AND the [E, H] message materialization of the reference
    mapping. ``edge_rel`` is not consumed — the static slices imply the
    relation. ``compute_dtype`` (e.g. "bfloat16") casts matmul operands
    only; accumulation stays f32. ``use_pallas`` swaps in the tiled
    VMEM-resident Pallas kernel (bit-identical; forward-only — callers
    that need gradients must leave it off)."""
    if use_pallas:
        from ..ops.pallas_segment import pallas_gather_matmul_segment as gms
    else:
        from ..ops.segment import gather_matmul_segment as gms
    agg = gms(
        h, layer["w_rel"], edge_src, edge_dst, edge_mask, rel_offsets,
        h.shape[0], slices_sorted=slices_sorted,
        compute_dtype=compute_dtype) * inv_deg[:, None]
    if compute_dtype is not None:
        self_t = jax.lax.dot(h.astype(compute_dtype),
                             layer["w_self"].astype(compute_dtype),
                             preferred_element_type=h.dtype)
    else:
        self_t = h @ layer["w_self"]
    return jax.nn.relu(self_t + agg + layer["b"]) + h


def forward(
    params: Params,
    features: jax.Array,        # [N, DIM] f32
    node_kind: jax.Array,       # [N] i32
    node_mask: jax.Array,       # [N] f32
    edge_src: jax.Array,        # [E] i32
    edge_dst: jax.Array,        # [E] i32
    edge_rel: jax.Array,        # [E] i32 (RelationKind; -1 = padding)
    edge_mask: jax.Array,       # [E] f32
    incident_nodes: jax.Array,  # [B] i32
    *,
    sorted_by_dst: bool = False,
    rel_offsets: tuple[int, ...] | None = None,
    slices_sorted: bool = False,
    compute_dtype: str | None = None,
    pallas: bool = False,
) -> jax.Array:
    """Logits [B, NUM_CLASSES] for each incident node.

    All keyword args are STATIC — bind them via functools.partial /
    static_argnames before jitting:

    * ``rel_offsets`` — a [R+1] tuple of per-relation edge-slice bounds
      switches to the relation-bucketed kernel (module docstring; edges
      MUST be laid out per the snapshot's (rel, dst) contract).
      ``slices_sorted=True`` additionally promises dst is non-decreasing
      within each slice (build_snapshot guarantees it; the streaming
      mirror promises it only until its first in-place churn).
      ``compute_dtype`` (e.g. "bfloat16") casts matmul operands only —
      accumulation stays f32.
    * ``pallas=True`` (requires ``rel_offsets``) dispatches the message
      passing to the tiled VMEM-resident Pallas kernel — the serving
      tier behind settings.gnn_pallas. Bit-identical logits, and since
      graft-fuse DIFFERENTIABLE: the kernel's ``custom_vjp`` runs the
      transposed-layout Pallas backward (dst-bucketed cotangent scatter
      + per-relation grad matmuls, f32 accumulation), so training and
      the online fine-tune (settings.learn_pallas_grads) can run this
      tier too — grads match ``jax.grad`` of the XLA kernel within f32
      tolerance (edge ``mask`` is treated as a 0/1 layout constant).
      Off-TPU the kernel auto-selects interpret mode.
    * ``sorted_by_dst=True`` (reference path only) promises the WHOLE
      edge_dst is non-decreasing, letting every segment-sum take the
      sorted fast path (measured 1.9x on the v5e scatter). Only a
      globally dst-sorted layout (pre-bucketing snapshots) satisfies it.
    """
    deg = jax.ops.segment_sum(edge_mask, edge_dst,
                              num_segments=features.shape[0],
                              indices_are_sorted=sorted_by_dst)
    inv_deg = jnp.where(deg > 0, 1.0 / jnp.maximum(deg, 1.0), 0.0)
    h = jax.nn.relu(features @ params["embed_w"] + params["embed_b"]
                    + params["kind_emb"][node_kind])
    h = h * node_mask[:, None]
    for layer in params["layers"]:
        if rel_offsets is not None:
            h = _message_pass_bucketed(h, layer, edge_src, edge_dst,
                                       edge_mask, rel_offsets, inv_deg,
                                       slices_sorted, compute_dtype,
                                       use_pallas=pallas)
        else:
            h = _message_pass(h, layer, edge_src, edge_dst, edge_rel,
                              edge_mask, inv_deg,
                              sorted_by_dst=sorted_by_dst)
    return h[incident_nodes] @ params["head_w"] + params["head_b"]


def loss_fn(
    params: Params,
    features, node_kind, node_mask, edge_src, edge_dst, edge_rel,
    edge_mask, incident_nodes, labels, label_mask,
    *,
    rel_offsets: tuple[int, ...] | None = None,
    slices_sorted: bool = False,
    compute_dtype: str | None = None,
    pallas: bool = False,
) -> jax.Array:
    """Masked mean cross-entropy over incident rows (static kwargs as in
    :func:`forward`). ``pallas=True`` trains through the Pallas kernel's
    custom_vjp (graft-fuse) — the settings.learn_pallas_grads tier."""
    logits = forward(params, features, node_kind, node_mask,
                     edge_src, edge_dst, edge_rel, edge_mask, incident_nodes,
                     rel_offsets=rel_offsets, slices_sorted=slices_sorted,
                     compute_dtype=compute_dtype, pallas=pallas)
    logp = jax.nn.log_softmax(logits, axis=-1)
    nll = -jnp.take_along_axis(logp, labels[:, None], axis=1)[:, 0]
    return (nll * label_mask).sum() / jnp.maximum(label_mask.sum(), 1.0)


def make_train_step(tx):
    """Single-device train step (optax transform tx); the sharded variant is
    parallel.sharded_gnn.make_sharded_train_step.

    ``rel_offsets``/``slices_sorted`` are static jit keys: pass the
    batch's offset tuple (NOT inside the batch pytree — tuple ints would
    trace) to train through the bucketed kernel; the per-relation ladder
    (graph/snapshot.py REL_SLICE_BUCKETS) keeps the distinct-tuple count
    — and so the compile count — small across episodes."""

    # params/opt_state are consumed and rebound every step: donating them
    # lets XLA update in place (no-op on CPU, halves optimizer-state HBM
    # traffic on device). Declared in analysis/ast_lint.JIT_DECLARATIONS.
    @partial(jax.jit, static_argnames=("rel_offsets", "slices_sorted"),
             donate_argnums=(0, 1))
    def step(params, opt_state, batch, rel_offsets=None,
             slices_sorted: bool = False):
        loss, grads = jax.value_and_grad(loss_fn)(
            params,
            batch["features"], batch["node_kind"], batch["node_mask"],
            batch["edge_src"], batch["edge_dst"], batch["edge_rel"],
            batch["edge_mask"],
            batch["incident_nodes"], batch["labels"], batch["label_mask"],
            rel_offsets=rel_offsets, slices_sorted=slices_sorted,
        )
        updates, opt_state = tx.update(grads, opt_state, params)
        params = jax.tree_util.tree_map(lambda p, u: p + u, params, updates)
        return params, opt_state, loss

    return step


def edges_sorted_by_dst(edge_dst) -> bool:
    """Host-side check of the sorted-segment-sum promise (one shared
    predicate — gnn_backend, device_metrics and the trainer all key the
    static ``sorted_by_dst`` flag off it)."""
    import numpy as np
    d = np.asarray(edge_dst)
    return bool((d[1:] >= d[:-1]).all())


def slices_sorted_by_dst(edge_dst, rel_offsets: tuple[int, ...]) -> bool:
    """Host-side check of the per-slice sorted promise for the bucketed
    kernel: dst non-decreasing WITHIN each relation slice (the global
    array is deliberately not sorted — slices restart at low rows)."""
    import numpy as np
    d = np.asarray(edge_dst)
    return all(
        bool((d[lo + 1:hi] >= d[lo:hi - 1]).all())
        for lo, hi in zip(rel_offsets[:-1], rel_offsets[1:]) if hi - lo > 1)


_jit_forward = None


def forward_batch(params: Params, batch: dict, *, bucketed: bool = True,
                  compute_dtype: str | None = None,
                  pallas: bool = False) -> jax.Array:
    """Score one snapshot batch with the best kernel for its layout.

    One shared dispatcher (gnn_backend, the trainer's eval paths and the
    oracle crosscheck all route through it): batches carrying a
    ``rel_offsets`` tuple take the relation-bucketed kernel (with the
    per-slice sorted fast path when the layout satisfies it), promoted to
    the Pallas serving tier when ``pallas=True`` (settings.gnn_pallas —
    forward-only, bit-identical); everything else — including
    ``bucketed=False``, the reference escape hatch — takes
    transform-then-gather with the global-sort fast path when the layout
    allows. All variants share ONE jitted callable keyed on the static
    args."""
    global _jit_forward
    if _jit_forward is None:
        _jit_forward = jax.jit(forward, static_argnames=(
            "sorted_by_dst", "rel_offsets", "slices_sorted",
            "compute_dtype", "pallas"))
    args = (params, batch["features"], batch["node_kind"],
            batch["node_mask"], batch["edge_src"], batch["edge_dst"],
            batch["edge_rel"], batch["edge_mask"], batch["incident_nodes"])
    offs = tuple(batch.get("rel_offsets") or ())
    if bucketed and offs:
        return _jit_forward(
            *args, rel_offsets=offs,
            slices_sorted=slices_sorted_by_dst(batch["edge_dst"], offs),
            compute_dtype=compute_dtype, pallas=pallas)
    return _jit_forward(
        *args, sorted_by_dst=edges_sorted_by_dst(batch["edge_dst"]))


def snapshot_batch(snapshot, labels=None) -> dict:
    """Pack a GraphSnapshot (+ optional int labels per incident) into the
    array batch consumed by forward/loss. ``rel_offsets`` rides along as a
    plain tuple — strip it (make_train_step) or route through
    forward_batch before handing the dict to jit as a pytree."""
    import numpy as np
    n_inc = snapshot.padded_incidents
    lab = np.full(n_inc, NUM_CLASSES - 1, dtype=np.int32)
    if labels is not None:
        lab[:len(labels)] = np.asarray(labels, dtype=np.int32)
    return {
        "rel_offsets": tuple(getattr(snapshot, "rel_offsets", ()) or ()),
        "features": snapshot.features,
        "node_kind": snapshot.node_kind,
        "node_mask": snapshot.node_mask,
        "edge_src": snapshot.edge_src,
        "edge_dst": snapshot.edge_dst,
        "edge_rel": snapshot.edge_rel,
        "edge_mask": snapshot.edge_mask,
        "incident_nodes": snapshot.incident_nodes,
        "labels": lab,
        "label_mask": snapshot.incident_mask,
    }
