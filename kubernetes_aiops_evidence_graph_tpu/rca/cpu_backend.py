"""CPU reference RCA backend — the accuracy oracle and the 40× denominator.

Reproduces the reference pipeline generate_hypotheses → rank
(rules_engine.py:200-234, hypothesis_ranker.py:13-80) as pure functions over
evidence dicts: signal fold → all-conditions rule match → constant-folded
confidence/ranking from the shared ruleset. The TPU backend must produce
identical top-1 rule ids and scores on the same snapshot (parity tests).
"""
from __future__ import annotations

import time
from typing import Iterable, Sequence
from uuid import UUID, uuid4

from ..models import Hypothesis, HypothesisCategory, HypothesisSource, RCAResult
from .ruleset import (
    RULES,
    Rule,
    UNKNOWN_ACTIONS,
    UNKNOWN_CONFIDENCE,
    UNKNOWN_FINAL_SCORE,
)
from .signals import Signals, condition_vector, extract_signals


def match_rules(signals: Signals) -> list[Rule]:
    """All-conditions-AND matching (rules_engine.py:359-378)."""
    conds = condition_vector(signals)
    return [r for r in RULES if all(conds[c] for c in r.conditions)]


def _hypothesis_from_rule(incident_id: UUID, rule: Rule, signals: Signals) -> Hypothesis:
    return Hypothesis(
        id=uuid4(),
        incident_id=incident_id,
        category=rule.category,
        title=rule.name,
        description=rule.description,
        confidence=rule.confidence,
        final_score=rule.final_score,
        support_count=len(rule.conditions),
        signal_strength=rule.evidence_strength,
        supporting_evidence_ids=[UUID(e) for e in signals.evidence_ids[:5] if _is_uuid(e)],
        recommended_actions=rule.recommended_actions,
        rule_id=rule.id,
        backend="cpu",
        generated_by=HypothesisSource.RULES_ENGINE,
    )


def _is_uuid(s: str) -> bool:
    try:
        UUID(s)
        return True
    except (ValueError, AttributeError, TypeError):
        return False


def _unknown_hypothesis(incident_id: UUID, signals: Signals) -> Hypothesis:
    """Fallback when nothing matches (rules_engine.py:426-447)."""
    return Hypothesis(
        id=uuid4(),
        incident_id=incident_id,
        category=HypothesisCategory.UNKNOWN,
        title="Unknown Issue",
        description="No specific pattern matched. Manual investigation required.",
        confidence=UNKNOWN_CONFIDENCE,
        final_score=UNKNOWN_FINAL_SCORE,
        rank=1,
        supporting_evidence_ids=[UUID(e) for e in signals.evidence_ids[:5] if _is_uuid(e)],
        recommended_actions=list(UNKNOWN_ACTIONS),
        rule_id="unknown",
        backend="cpu",
        generated_by=HypothesisSource.RULES_ENGINE,
    )


def rank(hypotheses: list[Hypothesis]) -> list[Hypothesis]:
    """Sort by final_score desc, assign 1-based ranks (hypothesis_ranker.py:67-71).

    Ties broken by rule-table order (stable sort), matching the CPU fold order.
    """
    ranked = sorted(hypotheses, key=lambda h: h.final_score, reverse=True)
    for i, h in enumerate(ranked):
        h.rank = i + 1
    return ranked


class CpuRcaBackend:
    """rca_backend="cpu" — scores incidents one at a time from evidence lists."""

    name = "cpu"

    def score_incident(self, incident_id: UUID, evidence: Iterable[dict]) -> RCAResult:
        t0 = time.perf_counter()
        signals = extract_signals(evidence)
        matched = match_rules(signals)
        if matched:
            hyps = [_hypothesis_from_rule(incident_id, r, signals) for r in matched]
        else:
            hyps = [_unknown_hypothesis(incident_id, signals)]
        hyps = rank(hyps)
        return RCAResult(
            incident_id=incident_id,
            hypotheses=hyps,
            top_hypothesis=hyps[0],
            rules_matched=[r.id for r in matched],
            analysis_duration_seconds=time.perf_counter() - t0,
            backend="cpu",
        )

    def score_batch(
        self, incidents: Sequence[tuple[UUID, Sequence[dict]]]
    ) -> list[RCAResult]:
        """Sequential per-incident loop — deliberately the reference's cost
        model (one Temporal activity per incident), used as the benchmark
        baseline."""
        return [self.score_incident(iid, ev) for iid, ev in incidents]
