"""Simulator CLI — fault injection + end-to-end RCA, hermetically.

The reference CLI (incident_simulator.py:274-314) applies failing workloads
to a live cluster and the operator watches Temporal. Here the same verbs run
the whole pipeline in-process: ``list`` shows scenarios, ``run`` injects one
or more scenarios into a generated cluster, collects evidence, builds the
graph, scores RCA on the chosen backend, and prints a JSON report.

    python -m kubernetes_aiops_evidence_graph_tpu.simulator.cli run \
        -s crashloop_deploy -s oom --pods 200 --backend both
"""
from __future__ import annotations

import argparse
import json
import sys

import numpy as np


def _cmd_list() -> int:
    from .scenarios import SCENARIOS
    for name, s in sorted(SCENARIOS.items()):
        print(f"{name:20s} alert={s.alertname:22s} expected_rule={s.expected_rule}")
    return 0


def _cmd_run(args: argparse.Namespace) -> int:
    from ..collectors import collect_all, default_collectors
    from ..config import load_settings
    from ..graph import GraphBuilder, build_snapshot
    from ..rca import RULES, get_backend
    from .scenarios import SCENARIOS, inject
    from .topology import generate_cluster

    for s in args.scenario:
        if s not in SCENARIOS:
            print(f"unknown scenario {s!r}; see `list`", file=sys.stderr)
            return 2

    settings = load_settings()
    cluster = generate_cluster(num_pods=args.pods, seed=args.seed)
    deploy_keys = sorted(cluster.deployments)
    rng = np.random.default_rng(args.seed)

    incidents = [
        inject(cluster, name, deploy_keys[(i * 7) % len(deploy_keys)], rng)
        for i, name in enumerate(args.scenario)
    ]
    builder = GraphBuilder()
    evidence = {}
    for inc in incidents:
        results = collect_all(inc, default_collectors(cluster, settings))
        builder.ingest(inc, results)
        evidence[inc.id] = [ev.model_dump(mode="json") for r in results for ev in r.evidence]

    report: dict = {"pods": args.pods, "incidents": []}
    snapshot = None
    if args.backend in ("tpu", "both"):
        snapshot = build_snapshot(builder.store, settings, now_s=cluster.now.timestamp())
        raw = get_backend("tpu").score_snapshot(snapshot)
        report["graph"] = {
            "nodes": snapshot.num_nodes, "edges": snapshot.num_edges,
            "padded_nodes": snapshot.padded_nodes,
            "device_seconds": round(raw["device_seconds"], 4),
        }
    for i, inc in enumerate(incidents):
        entry = {
            "scenario": inc.labels.get("scenario"),
            "incident": str(inc.id),
            "expected_rule": SCENARIOS[inc.labels["scenario"]].expected_rule,
        }
        if args.backend in ("cpu", "both"):
            top = get_backend("cpu").score_incident(inc.id, evidence[inc.id]).top_hypothesis
            entry["cpu_top1"] = {"rule": top.rule_id, "confidence": top.confidence,
                                 "score": top.final_score}
        if args.backend in ("tpu", "both"):
            row = list(raw["incident_ids"]).index(f"incident:{inc.id}")
            rule = RULES[int(raw["top_rule_index"][row])].id if raw["any_match"][row] else "unknown"
            entry["tpu_top1"] = {"rule": rule,
                                 "confidence": round(float(raw["top_confidence"][row]), 3),
                                 "score": round(float(raw["top_score"][row]), 4)}
        report["incidents"].append(entry)
    print(json.dumps(report, indent=2))
    return 0


def _live_injector(args: argparse.Namespace):
    from ..collectors.live import LiveClusterBackend
    from ..config import load_settings
    from .live_faults import LiveFaultInjector

    backend = LiveClusterBackend(load_settings(),
                                 k8s_url=args.k8s_url or None)
    return LiveFaultInjector(backend)


def _cmd_create(args: argparse.Namespace) -> int:
    created = _live_injector(args).create(args.scenario, args.namespace)
    print(json.dumps({"created": created}))
    return 0 if created else 1


def _cmd_cleanup(args: argparse.Namespace) -> int:
    removed = _live_injector(args).cleanup(args.namespace)
    print(json.dumps({"removed": removed}))
    return 0


def main(argv: list[str] | None = None) -> int:
    parser = argparse.ArgumentParser(prog="kaeg-sim", description=__doc__)
    sub = parser.add_subparsers(dest="cmd", required=True)
    sub.add_parser("list", help="list fault scenarios")
    run = sub.add_parser("run", help="inject scenarios and run RCA hermetically")
    run.add_argument("-s", "--scenario", action="append", required=True)
    run.add_argument("--pods", type=int, default=200)
    run.add_argument("--seed", type=int, default=0)
    run.add_argument("--backend", choices=("cpu", "tpu", "both"), default="both")
    # live-cluster fault injection (reference incident_simulator.py:274-314)
    create = sub.add_parser("create", help="apply a failing workload to a live cluster")
    create.add_argument("-s", "--scenario", required=True,
                        choices=("crashloop", "oom", "imagepull", "slowapp"))
    create.add_argument("-n", "--namespace", default="default")
    create.add_argument("--k8s-url", default="")
    cleanup = sub.add_parser("cleanup", help="remove injected workloads (label simulator=kaeg-test)")
    cleanup.add_argument("-n", "--namespace", default="default")
    cleanup.add_argument("--k8s-url", default="")
    args = parser.parse_args(argv)
    if args.cmd == "list":
        return _cmd_list()
    if args.cmd == "create":
        return _cmd_create(args)
    if args.cmd == "cleanup":
        return _cmd_cleanup(args)
    return _cmd_run(args)


if __name__ == "__main__":
    raise SystemExit(main())
