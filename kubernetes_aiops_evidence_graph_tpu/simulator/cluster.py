"""FakeCluster — hermetic, replayable cluster backend.

The reference's only test story is live fault injection into a real
kind/minikube cluster (src/simulator/incident_simulator.py, SURVEY.md §4).
This FakeCluster replaces the K8s API + Loki + Prometheus trio with a
deterministic in-memory state machine that the collectors query through the
same backend interface they use against real endpoints — so the whole
pipeline runs hermetically at 200 → 50k pod scale (BASELINE.json configs).
"""
from __future__ import annotations

from dataclasses import dataclass, field
from datetime import datetime, timedelta
from typing import Optional

from ..utils.timeutils import utcnow


@dataclass
class PodState:
    name: str
    namespace: str
    deployment: str
    service: str
    node: str
    phase: str = "Running"
    ready: bool = True
    restart_count: int = 0
    waiting_reason: Optional[str] = None
    terminated_reason: Optional[str] = None
    not_ready_seconds: float = 0.0
    readiness_probe_failing: bool = False
    started_at: Optional[datetime] = None       # status.startTime
    creation_ts: Optional[datetime] = None      # metadata.creationTimestamp
    # review-surface detail (reference kubernetes_collector.py:194-267):
    # populated from the wire by the live backend; None on the fake
    # cluster, where the collector synthesizes a one-container view from
    # the scalars above (pod_detail in collectors/kubernetes.py)
    conditions: Optional[list] = None          # [{type, status, reason}]
    container_statuses: Optional[list] = None  # per-container state detail
    resources: Optional[dict] = None           # {container: {requests, limits}}
    labels: Optional[dict] = None


@dataclass
class DeploymentState:
    name: str
    namespace: str
    service: str
    replicas: int = 3
    ready_replicas: int = 3
    revision: int = 1
    image: str = "registry.local/app:v1"
    prev_image: Optional[str] = None
    changed_at: Optional[datetime] = None


@dataclass
class NodeState:
    name: str
    # condition -> "True"/"False"; Ready defaults True, pressures default False
    conditions: dict[str, str] = field(default_factory=lambda: {"Ready": "True"})


@dataclass
class ServiceState:
    name: str
    namespace: str
    deployment: str
    calls: list[str] = field(default_factory=list)  # downstream service names


@dataclass
class HPAState:
    name: str
    namespace: str
    deployment: str
    min_replicas: int = 1
    max_replicas: int = 10
    current_replicas: int = 3
    at_max: bool = False


@dataclass
class ConfigMapState:
    name: str
    namespace: str
    changed_at: Optional[datetime] = None
    mounted_by: list[str] = field(default_factory=list)  # deployment names


@dataclass
class EventState:
    namespace: str
    involved_object: str
    reason: str
    type: str = "Warning"
    message: str = ""
    timestamp: Optional[datetime] = None


@dataclass
class ServiceMetrics:
    memory_pct: float = 55.0
    error_rate: float = 0.001
    p99_latency_s: float = 0.12
    cpu_throttle_ratio: float = 0.02
    oom_events: float = 0.0
    restarts_rate: float = 0.0
    hpa_at_max: float = 0.0  # 0/1 gauge
    # optional per-query time series [(epoch_s, value), ...]; when present
    # query_metric_range serves it verbatim (trend/spike scenarios), else a
    # flat series is synthesized from the instant value above
    series: dict[str, list[tuple[float, float]]] = field(default_factory=dict)


class FakeCluster:
    """In-memory cluster implementing the ClusterBackend query surface."""

    def __init__(self, seed: int = 0) -> None:
        self.seed = seed
        self.pods: dict[str, PodState] = {}
        self.deployments: dict[str, DeploymentState] = {}
        self.nodes: dict[str, NodeState] = {}
        self.services: dict[str, ServiceState] = {}
        self.hpas: dict[str, HPAState] = {}
        self.configmaps: dict[str, ConfigMapState] = {}
        self.events: list[EventState] = []
        self.pod_logs: dict[str, list[str]] = {}
        self.metrics: dict[str, ServiceMetrics] = {}
        self.now: datetime = utcnow()
        self._pod_index: dict[tuple[str, str], list[PodState]] | None = None
        self._pod_index_size: int = -1

    # -- keys -------------------------------------------------------------

    @staticmethod
    def _key(namespace: str, name: str) -> str:
        return f"{namespace}/{name}"

    # -- ClusterBackend query surface (used by collectors) ----------------

    def invalidate_index(self) -> None:
        """Drop the service index. Adds/removes are auto-detected by size;
        call this only when *replacing* a pod under the same key."""
        self._pod_index = None

    def add_pod(self, pod: PodState) -> None:
        """Insert a pod keeping the service index incremental — a churn
        stream at 1k events/s must not pay an O(pods) index rebuild per
        create (the rebuild dominated the streaming bench host loop)."""
        import bisect
        key = self._key(pod.namespace, pod.name)
        old = self.pods.get(key)
        if old is not None:
            # replacement under the same key: evict the stale object from
            # its index list first, or it would keep serving dead state
            self.remove_pod(pod.namespace, pod.name)
        self.pods[key] = pod
        if self._pod_index is not None:
            lst = self._pod_index.setdefault((pod.namespace, pod.service), [])
            bisect.insort(lst, pod, key=lambda p: p.name)
            self._pod_index_size += 1

    def remove_pod(self, namespace: str, name: str):
        """Remove a pod, updating the service index in place."""
        p = self.pods.pop(self._key(namespace, name), None)
        if p is not None and self._pod_index is not None:
            lst = self._pod_index.get((p.namespace, p.service))
            if lst is None:
                self._pod_index = None   # index diverged; full rebuild
            else:
                try:
                    lst.remove(p)       # identity-equal object reference
                    self._pod_index_size -= 1
                except ValueError:
                    self._pod_index = None   # replaced object; full rebuild
        return p

    def _pods_by_service(self) -> dict[tuple[str, str], list[PodState]]:
        # auto-invalidate when pods were added/removed (size change); scenario
        # code mutates existing PodState objects in place, which needs no
        # invalidation because the index holds object references
        if self._pod_index is None or self._pod_index_size != len(self.pods):
            idx: dict[tuple[str, str], list[PodState]] = {}
            for p in self.pods.values():
                idx.setdefault((p.namespace, p.service), []).append(p)
            for lst in idx.values():
                lst.sort(key=lambda p: p.name)
            self._pod_index = idx
            self._pod_index_size = len(self.pods)
        return self._pod_index

    def list_pods(self, namespace: str, service: str | None = None) -> list[PodState]:
        if service is not None:
            return list(self._pods_by_service().get((namespace, service), ()))
        out = [p for p in self.pods.values() if p.namespace == namespace]
        return sorted(out, key=lambda p: p.name)

    def list_deployments(self, namespace: str, service: str | None = None) -> list[DeploymentState]:
        out = [
            d for d in self.deployments.values()
            if d.namespace == namespace and (service is None or d.service == service)
        ]
        return sorted(out, key=lambda d: d.name)

    def list_nodes(self) -> list[NodeState]:
        return sorted(self.nodes.values(), key=lambda n: n.name)

    def list_hpas(self, namespace: str, service: str | None = None) -> list[HPAState]:
        out = [
            h for h in self.hpas.values()
            if h.namespace == namespace
            and (service is None or self.deployments.get(self._key(namespace, h.deployment),
                                                         DeploymentState("", "", "")).service == service)
        ]
        return sorted(out, key=lambda h: h.name)

    def list_configmaps(self, namespace: str) -> list[ConfigMapState]:
        return sorted(
            (c for c in self.configmaps.values() if c.namespace == namespace),
            key=lambda c: c.name,
        )

    def list_events(self, namespace: str, since: datetime) -> list[EventState]:
        return [
            e for e in self.events
            if e.namespace == namespace and e.timestamp is not None and e.timestamp >= since
        ]

    def query_logs(self, namespace: str, service: str, limit: int = 1000) -> list[str]:
        """Loki query_range analog: newest-first lines for a service's pods
        (logs_collector.py:80-116)."""
        lines: list[str] = []
        for p in self.list_pods(namespace, service):
            lines.extend(self.pod_logs.get(self._key(namespace, p.name), ()))
        return lines[-limit:][::-1]

    def query_metric(self, namespace: str, service: str, query_name: str) -> float | None:
        """Prometheus instant-value analog, keyed by query name."""
        m = self.metrics.get(self._key(namespace, service))
        if m is None:
            return None
        series = m.series.get(query_name)
        if series:
            return series[-1][1]
        table = {
            "memory_usage_pct": m.memory_pct,
            "error_rate": m.error_rate,
            "latency_p99_seconds": m.p99_latency_s,
            "cpu_throttle_ratio": m.cpu_throttle_ratio,
            "oom_events": m.oom_events,
            "pod_restarts": m.restarts_rate,
            "hpa_at_max": m.hpa_at_max,
        }
        return table.get(query_name)

    def query_metric_range(self, namespace: str, service: str,
                           query_name: str, start_s: float,
                           end_s: float) -> list[tuple[float, float]]:
        """Prometheus query_range analog (metrics_collector.py:161-185):
        serves the scenario-set series clipped to the window, else a
        deterministic flat series synthesized from the instant value — so
        the hermetic path exercises the same series-stats code as live."""
        m = self.metrics.get(self._key(namespace, service))
        if m is None:
            return []
        series = m.series.get(query_name)
        if series:
            return [(t, v) for t, v in series if start_s <= t <= end_s]
        value = self.query_metric(namespace, service, query_name)
        if value is None or end_s <= start_s:
            return []
        step = max(15.0, (end_s - start_s) / 100.0)
        n = max(2, int((end_s - start_s) / step))
        return [(start_s + i * (end_s - start_s) / (n - 1), float(value))
                for i in range(n)]

    def set_metric_series(self, namespace: str, service: str,
                          query_name: str, values: list[float],
                          window_s: float = 900.0) -> None:
        """Spread ``values`` evenly over the trailing ``window_s`` seconds
        ending at cluster ``now`` — scenario/test helper for trend series."""
        from ..utils.timeutils import to_epoch_s
        end = to_epoch_s(self.now)
        n = len(values)
        ts = [end - window_s + (i + 1) * window_s / n for i in range(n)]
        self.service_metrics(namespace, service).series[query_name] = (
            list(zip(ts, values)))

    def rollout_history(self, namespace: str, deployment: str) -> list[dict]:
        d = self.deployments.get(self._key(namespace, deployment))
        if d is None:
            return []
        hist = [{
            "revision": d.revision,
            "image": d.image,
            "changed_at": d.changed_at,
        }]
        if d.prev_image is not None:
            hist.append({
                "revision": d.revision - 1,
                "image": d.prev_image,
                "changed_at": None,
            })
        return hist

    # -- mutation helpers used by scenarios/stream ------------------------

    def add_event(self, namespace: str, obj: str, reason: str, message: str = "") -> None:
        self.events.append(EventState(
            namespace=namespace, involved_object=obj, reason=reason,
            message=message, timestamp=self.now,
        ))

    def set_logs(self, namespace: str, pod: str, lines: list[str]) -> None:
        self.pod_logs[self._key(namespace, pod)] = lines

    def service_metrics(self, namespace: str, service: str) -> ServiceMetrics:
        return self.metrics.setdefault(self._key(namespace, service), ServiceMetrics())

    def advance(self, seconds: float) -> None:
        self.now = self.now + timedelta(seconds=seconds)

    # -- ClusterAdminBackend: remediation actions -------------------------
    # These model how real K8s reacts to the corresponding executor verbs;
    # a restarted/rolled-back pod comes back healthy unless the underlying
    # fault is environmental, so the verifier sees genuine improvement.

    def _node_healthy(self, name: str) -> bool:
        node = self.nodes.get(name)
        if node is None:
            return True
        if node.conditions.get("Ready", "True") != "True":
            return False
        return not any(
            node.conditions.get(c) == "True"
            for c in ("MemoryPressure", "DiskPressure", "PIDPressure",
                      "NetworkUnavailable"))

    def _heal_pod(self, p: PodState) -> None:
        """Restart outcome: healthy unless the fault is environmental — a pod
        rescheduled onto a sick node stays not-ready."""
        p.waiting_reason = None
        p.terminated_reason = None
        p.restart_count = 0
        p.readiness_probe_failing = False
        p.started_at = self.now
        if self._node_healthy(p.node):
            p.phase = "Running"
            p.ready = True
            p.not_ready_seconds = 0.0
        else:
            p.phase = "Pending"
            p.ready = False

    def _recompute_ready(self, namespace: str, deployment: str) -> None:
        d = self.deployments.get(self._key(namespace, deployment))
        if d is not None:
            d.ready_replicas = sum(
                1 for p in self.list_pods(namespace, d.service)
                if p.deployment == deployment and p.ready)

    def _heal_service_metrics(self, namespace: str, service: str) -> None:
        key = self._key(namespace, service)
        if key in self.metrics:  # reset existing gauges, don't invent new ones
            self.metrics[key] = ServiceMetrics()
        for p in self.list_pods(namespace, service):
            self.pod_logs.pop(self._key(namespace, p.name), None)

    def delete_pod(self, namespace: str, name: str) -> bool:
        """Delete → controller recreates it (executor.py:86-134 analog)."""
        key = self._key(namespace, name)
        p = self.pods.get(key)
        if p is None:
            return False
        self._heal_pod(p)
        self._recompute_ready(namespace, p.deployment)
        return True

    def restart_deployment(self, namespace: str, deployment: str) -> bool:
        key = self._key(namespace, deployment)
        d = self.deployments.get(key)
        if d is None:
            return False
        for p in self.list_pods(namespace, d.service):
            if p.deployment == deployment:
                self._heal_pod(p)
        self._recompute_ready(namespace, deployment)
        self._heal_service_metrics(namespace, d.service)
        return True

    def rollback_deployment(self, namespace: str, deployment: str) -> bool:
        """Restore previous template (executor.py:177-234 analog)."""
        key = self._key(namespace, deployment)
        d = self.deployments.get(key)
        if d is None or d.prev_image is None:
            return False
        d.image, d.prev_image = d.prev_image, d.image
        d.revision += 1
        d.changed_at = self.now
        return self.restart_deployment(namespace, deployment)

    def _schedulable_node(self, preferred: str | None = None) -> str:
        """Pick a target node honoring cordons (Unschedulable)."""
        if preferred is not None:
            node = self.nodes.get(preferred)
            if node is not None and node.conditions.get("Unschedulable") != "True":
                return preferred
        for name in sorted(self.nodes):
            if self.nodes[name].conditions.get("Unschedulable") != "True":
                return name
        return preferred or "node-0"

    def scale_deployment(self, namespace: str, deployment: str, replicas: int) -> bool:
        key = self._key(namespace, deployment)
        d = self.deployments.get(key)
        if d is None:
            return False
        pods = [p for p in self.list_pods(namespace, d.service)
                if p.deployment == deployment]
        if replicas < len(pods):  # scale down removes pods
            for p in pods[replicas:]:
                del self.pods[self._key(namespace, p.name)]
        else:
            template = pods[0] if pods else None
            existing = {p.name for p in pods}
            i = 0
            while len(existing) < replicas:
                name = f"{deployment}-scaled-{i}"
                i += 1
                if name in existing:
                    continue
                existing.add(name)
                self.pods[self._key(namespace, name)] = PodState(
                    name=name, namespace=namespace, deployment=deployment,
                    service=d.service,
                    node=self._schedulable_node(template.node if template else None),
                    started_at=self.now)
        d.replicas = replicas
        self.invalidate_index()
        self._recompute_ready(namespace, deployment)
        return True

    def cordon_node(self, name: str) -> bool:
        """Mark unschedulable; surfaced by the kubernetes collector and
        honored by _schedulable_node for future placements."""
        node = self.nodes.get(name)
        if node is None:
            return False
        node.conditions["Unschedulable"] = "True"
        return True

    def uncordon_node(self, name: str) -> bool:
        """Clear the cordon (graft-saga compensation inverse)."""
        node = self.nodes.get(name)
        if node is None:
            return False
        node.conditions.pop("Unschedulable", None)
        return True
