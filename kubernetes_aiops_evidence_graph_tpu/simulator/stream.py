"""Churn event stream — deterministic cluster mutations at rate.

BASELINE configs[4]: "streaming graph updates (pod churn @1k events/sec)
with incremental TPU re-scoring". This generator emits a seeded, replayable
sequence of cluster events (pod restarts, reschedules, status flips, metric
drifts, rollouts) that the streaming scorer applies as feature/graph deltas
without rebuilding the snapshot.
"""
from __future__ import annotations

from dataclasses import dataclass, field
from typing import Iterator

import numpy as np

from .cluster import FakeCluster


@dataclass(frozen=True)
class ChurnEvent:
    kind: str                  # pod_restart|pod_flip|reschedule|metric_drift|rollout
    namespace: str
    name: str                  # pod or deployment name
    payload: dict = field(default_factory=dict)


_KINDS = ("pod_restart", "pod_flip", "reschedule", "metric_drift", "rollout")
_WEIGHTS = (0.45, 0.25, 0.1, 0.15, 0.05)


def churn_events(
    cluster: FakeCluster,
    count: int,
    seed: int = 0,
) -> Iterator[ChurnEvent]:
    """Yield `count` deterministic events referencing real cluster objects."""
    rng = np.random.default_rng(seed)
    pod_keys = sorted(cluster.pods)
    deploy_keys = sorted(cluster.deployments)
    node_names = sorted(cluster.nodes)
    if not pod_keys or not deploy_keys:
        return
    kinds = rng.choice(len(_KINDS), size=count, p=_WEIGHTS)
    for i in range(count):
        kind = _KINDS[kinds[i]]
        if kind in ("pod_restart", "pod_flip", "reschedule"):
            key = pod_keys[int(rng.integers(0, len(pod_keys)))]
            pod = cluster.pods[key]
            payload: dict = {}
            if kind == "pod_restart":
                payload = {"restart_delta": int(rng.integers(1, 3))}
            elif kind == "pod_flip":
                payload = {"ready": bool(rng.random() < 0.5)}
            else:
                payload = {"node": node_names[int(rng.integers(0, len(node_names)))]}
            yield ChurnEvent(kind, pod.namespace, pod.name, payload)
        elif kind == "metric_drift":
            key = deploy_keys[int(rng.integers(0, len(deploy_keys)))]
            d = cluster.deployments[key]
            yield ChurnEvent(kind, d.namespace, d.service, {
                "memory_pct": float(np.clip(rng.normal(60, 20), 5, 99)),
                "error_rate": float(np.clip(rng.exponential(0.01), 0, 0.5)),
            })
        else:  # rollout
            key = deploy_keys[int(rng.integers(0, len(deploy_keys)))]
            d = cluster.deployments[key]
            yield ChurnEvent(kind, d.namespace, d.name, {})


def apply_event(cluster: FakeCluster, event: ChurnEvent) -> list[str]:
    """Mutate cluster state; returns the graph node ids whose features
    changed (the delta set for incremental re-scoring)."""
    touched: list[str] = []
    key = f"{event.namespace}/{event.name}"
    if event.kind == "pod_restart":
        p = cluster.pods.get(key)
        if p is not None:
            p.restart_count += event.payload.get("restart_delta", 1)
            touched.append(f"pod:{p.namespace}:{p.name}")
    elif event.kind == "pod_flip":
        p = cluster.pods.get(key)
        if p is not None:
            p.ready = event.payload["ready"]
            p.not_ready_seconds = 0.0 if p.ready else 360.0
            touched.append(f"pod:{p.namespace}:{p.name}")
    elif event.kind == "reschedule":
        p = cluster.pods.get(key)
        if p is not None:
            p.node = event.payload["node"]
            touched.append(f"pod:{p.namespace}:{p.name}")
    elif event.kind == "metric_drift":
        m = cluster.service_metrics(event.namespace, event.name)
        m.memory_pct = event.payload["memory_pct"]
        m.error_rate = event.payload["error_rate"]
        touched.append(f"service:{event.namespace}:{event.name}")
    elif event.kind == "rollout":
        d = cluster.deployments.get(key)
        if d is not None:
            d.revision += 1
            d.prev_image = d.image
            d.image = d.image.rsplit(":", 1)[0] + f":v{d.revision}"
            d.changed_at = cluster.now
            touched.append(f"deployment:{d.namespace}:{d.name}")
    return touched


def sync_touched_to_store(cluster: FakeCluster, store, touched: list[str]) -> None:
    """Propagate mutated cluster state onto the graph-store node property
    bags so feature re-extraction sees the new values (the kube-state sync
    delta path; full sync is graph.topology_sync)."""
    for nid in touched:
        kind, rest = nid.split(":", 1)
        node = store.get_node(nid)
        if node is None:
            continue
        if kind == "pod":
            ns, name = rest.split(":", 1)
            p = cluster.pods.get(f"{ns}/{name}")
            if p is not None:
                node_obj = store._nodes[nid]  # in-place property update
                node_obj.properties.update(
                    waiting_reason=p.waiting_reason,
                    terminated_reason=p.terminated_reason,
                    restart_count=p.restart_count, ready=p.ready,
                    not_ready_seconds=p.not_ready_seconds, phase=p.phase)
        elif kind == "service":
            ns, name = rest.split(":", 1)
            m = cluster.metrics.get(f"{ns}/{name}")
            if m is not None:
                store._nodes[nid].properties.update(
                    memory_usage_high=m.memory_pct > 90,
                    latency_high=m.p99_latency_s > 1.0)
        elif kind == "deployment":
            ns, name = rest.split(":", 1)
            d = cluster.deployments.get(f"{ns}/{name}")
            if d is not None:
                store._nodes[nid].properties.update(
                    revision=d.revision,
                    is_recent_change=True,
                    changed_at=d.changed_at.isoformat() if d.changed_at else None)
