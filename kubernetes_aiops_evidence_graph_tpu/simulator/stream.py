"""Churn event stream — deterministic cluster mutations at rate.

BASELINE configs[4]: "streaming graph updates (pod churn @1k events/sec)
with incremental TPU re-scoring". This generator emits a seeded, replayable
sequence of cluster events (pod restarts, reschedules, status flips, metric
drifts, rollouts) that the streaming scorer applies as feature/graph deltas
without rebuilding the snapshot.
"""
from __future__ import annotations

from dataclasses import dataclass, field
from typing import Iterator

import numpy as np

from .cluster import FakeCluster


@dataclass(frozen=True)
class ChurnEvent:
    kind: str                  # see _KINDS
    namespace: str
    name: str                  # pod or deployment name (or incident uid)
    payload: dict = field(default_factory=dict)


# Full event mix (VERDICT r1 item 2): mutate-in-place kinds PLUS structural
# growth — pod creation/deletion and incident arrival/closure, the events
# Neo4j MERGE absorbs for free in the reference (neo4j.py:95-166).
_KINDS = ("pod_restart", "pod_flip", "reschedule", "metric_drift", "rollout",
          "pod_create", "pod_delete", "incident_arrival", "incident_close")
_WEIGHTS = (0.34, 0.20, 0.08, 0.15, 0.05, 0.08, 0.05, 0.03, 0.02)


def churn_events(
    cluster: FakeCluster,
    count: int,
    seed: int = 0,
    incident_ids: tuple[str, ...] = (),
    structural: bool = True,
) -> Iterator[ChurnEvent]:
    """Yield `count` deterministic events referencing real cluster objects.

    The generator tracks its own created/deleted pods and open incidents
    (seeded from ``incident_ids``) so delete/close events always reference
    something the stream created or was told about. ``structural=False``
    restores the round-1 mutate-in-place-only mix."""
    rng = np.random.default_rng(seed)
    pod_keys = sorted(cluster.pods)
    deploy_keys = sorted(cluster.deployments)
    node_names = sorted(cluster.nodes)
    if not pod_keys or not deploy_keys:
        return
    open_incidents = list(incident_ids)
    created_serial = 0
    if structural:
        kinds_i, weights = _KINDS, _WEIGHTS
    else:
        kinds_i, weights = _KINDS[:5], tuple(
            w / sum(_WEIGHTS[:5]) for w in _WEIGHTS[:5])
    kinds = rng.choice(len(kinds_i), size=count, p=weights)
    for i in range(count):
        kind = kinds_i[kinds[i]]
        if kind in ("pod_restart", "pod_flip", "reschedule", "pod_delete"):
            key = pod_keys[int(rng.integers(0, len(pod_keys)))]
            ns, name = key.split("/", 1)
            payload: dict = {}
            if kind == "pod_restart":
                payload = {"restart_delta": int(rng.integers(1, 3))}
            elif kind == "pod_flip":
                payload = {"ready": bool(rng.random() < 0.5)}
            elif kind == "reschedule":
                payload = {"node": node_names[int(rng.integers(0, len(node_names)))]}
            else:  # pod_delete
                if len(pod_keys) <= 1:
                    continue
                pod_keys.remove(key)
            yield ChurnEvent(kind, ns, name, payload)
        elif kind == "metric_drift":
            key = deploy_keys[int(rng.integers(0, len(deploy_keys)))]
            d = cluster.deployments[key]
            yield ChurnEvent(kind, d.namespace, d.service, {
                "memory_pct": float(np.clip(rng.normal(60, 20), 5, 99)),
                "error_rate": float(np.clip(rng.exponential(0.01), 0, 0.5)),
            })
        elif kind == "rollout":
            key = deploy_keys[int(rng.integers(0, len(deploy_keys)))]
            d = cluster.deployments[key]
            yield ChurnEvent(kind, d.namespace, d.name, {})
        elif kind == "pod_create":
            key = deploy_keys[int(rng.integers(0, len(deploy_keys)))]
            d = cluster.deployments[key]
            created_serial += 1
            name = f"{d.name}-s{created_serial}"
            pod_keys.append(f"{d.namespace}/{name}")
            pod_keys.sort()
            attach = None
            if open_incidents and rng.random() < 0.5:
                attach = open_incidents[int(rng.integers(0, len(open_incidents)))]
            yield ChurnEvent(kind, d.namespace, name, {
                "deployment": d.name, "service": d.service,
                "node": node_names[int(rng.integers(0, len(node_names)))],
                "attach_to": attach,   # becomes evidence of an open incident
            })
        elif kind == "incident_arrival":
            key = deploy_keys[int(rng.integers(0, len(deploy_keys)))]
            d = cluster.deployments[key]
            uid = f"stream-{seed}-{i}"
            open_incidents.append(uid)
            yield ChurnEvent(kind, d.namespace, uid, {
                "deployment": d.name, "service": d.service,
                "max_evidence": int(rng.integers(2, 6)),
            })
        else:  # incident_close
            if not open_incidents:
                continue
            uid = open_incidents.pop(int(rng.integers(0, len(open_incidents))))
            yield ChurnEvent(kind, "", uid, {})


def apply_event(cluster: FakeCluster, event: ChurnEvent) -> list[str]:
    """Mutate cluster state; returns the graph node ids whose features
    changed (the delta set for incremental re-scoring)."""
    touched: list[str] = []
    key = f"{event.namespace}/{event.name}"
    if event.kind == "pod_restart":
        p = cluster.pods.get(key)
        if p is not None:
            p.restart_count += event.payload.get("restart_delta", 1)
            touched.append(f"pod:{p.namespace}:{p.name}")
    elif event.kind == "pod_flip":
        p = cluster.pods.get(key)
        if p is not None:
            p.ready = event.payload["ready"]
            p.not_ready_seconds = 0.0 if p.ready else 360.0
            touched.append(f"pod:{p.namespace}:{p.name}")
    elif event.kind == "reschedule":
        p = cluster.pods.get(key)
        if p is not None:
            p.node = event.payload["node"]
            touched.append(f"pod:{p.namespace}:{p.name}")
    elif event.kind == "metric_drift":
        m = cluster.service_metrics(event.namespace, event.name)
        m.memory_pct = event.payload["memory_pct"]
        m.error_rate = event.payload["error_rate"]
        touched.append(f"service:{event.namespace}:{event.name}")
    elif event.kind == "rollout":
        d = cluster.deployments.get(key)
        if d is not None:
            d.revision += 1
            d.prev_image = d.image
            d.image = d.image.rsplit(":", 1)[0] + f":v{d.revision}"
            d.changed_at = cluster.now
            touched.append(f"deployment:{d.namespace}:{d.name}")
    elif event.kind == "pod_create":
        from .cluster import PodState
        cluster.add_pod(PodState(
            name=event.name, namespace=event.namespace,
            deployment=event.payload["deployment"],
            service=event.payload["service"], node=event.payload["node"],
            started_at=cluster.now))
        touched.append(f"pod:{event.namespace}:{event.name}")
    elif event.kind == "pod_delete":
        if cluster.remove_pod(event.namespace, event.name) is not None:
            touched.append(f"pod:{event.namespace}:{event.name}")
    # incident_arrival / incident_close don't touch cluster state: incidents
    # live in the graph/store; stream_step() handles them there
    return touched


class _StoreOnly:
    """stream_step scorer stand-in that drops every mirror call: drivers
    that serve through a journal-draining path (scorer.sync(), serve(),
    or the graft-shield's write-ahead staging) mutate ONLY the store and
    let the scorer catch up from its change journal — the shield's
    durability guarantee covers exactly what flows through that journal."""

    def __getattr__(self, name):
        return lambda *a, **k: None


_STORE_ONLY = _StoreOnly()


def store_step(cluster: FakeCluster, store, event: ChurnEvent) -> list[str]:
    """stream_step without the direct scorer mirroring: cluster + store
    only. Feature mutations are journaled via ``store.touch_nodes`` (the
    in-place property path bypasses upsert), so a journal-draining
    consumer — scorer.sync(), serve(), the graft-shield WAL — sees every
    change. The full-mix driver for journal-synced serving (graft-shield
    fault-injection tests, recovery bench)."""
    touched = stream_step(cluster, store, _STORE_ONLY, event)
    store.touch_nodes(touched)
    return touched


def stream_step(cluster: FakeCluster, store, scorer, event: ChurnEvent) -> list[str]:
    """Apply ONE event everywhere: cluster state, graph store (authoritative
    — rebuilds read it), and the streaming scorer's incremental state.
    Returns the touched node ids. This is the full-mix driver the bench and
    the rebuild-parity tests share."""
    from ..graph import ids
    from ..models import GraphEntity, GraphRelation

    if event.kind == "reschedule":
        pod_nid = ids.pod_id(event.namespace, event.name)
        node_nid = ids.node_id(event.payload["node"])
        touched = apply_event(cluster, event)
        sync_touched_to_store(cluster, store, touched)
        if touched and store.get_node(pod_nid) is not None:
            for old in store.relations_from(pod_nid, "SCHEDULED_ON"):
                if old != node_nid:
                    store.remove_relation(pod_nid, old, "SCHEDULED_ON")
            store.upsert_relations([GraphRelation(
                source_id=pod_nid, target_id=node_nid,
                relation_type="SCHEDULED_ON")])
            scorer.schedule_pod(pod_nid, node_nid)
        scorer.update_nodes(touched)
        return touched

    if event.kind == "pod_create":
        touched = apply_event(cluster, event)
        p = cluster.pods[f"{event.namespace}/{event.name}"]
        pod_nid = ids.pod_id(p.namespace, p.name)
        store.upsert_entities([GraphEntity(
            id=pod_nid, type="Pod",
            properties={"waiting_reason": p.waiting_reason,
                        "terminated_reason": p.terminated_reason,
                        "restart_count": p.restart_count, "ready": p.ready,
                        "phase": p.phase})])
        store.upsert_relations([
            GraphRelation(source_id=pod_nid,
                          target_id=ids.node_id(p.node),
                          relation_type="SCHEDULED_ON"),
            GraphRelation(source_id=ids.deployment_id(p.namespace, p.deployment),
                          target_id=pod_nid, relation_type="OWNS"),
        ])
        scorer.add_entity(pod_nid)
        scorer.schedule_pod(pod_nid, ids.node_id(p.node))
        attach = event.payload.get("attach_to")
        if attach:
            inc_nid = attach if attach.startswith("incident:") \
                else f"incident:{attach}"
            if store.get_node(inc_nid) is not None:
                store.upsert_relations([GraphRelation(
                    source_id=inc_nid, target_id=pod_nid,
                    relation_type="AFFECTS")])
                scorer.add_evidence(inc_nid, pod_nid)
        return touched

    if event.kind == "pod_delete":
        pod_nid = ids.pod_id(event.namespace, event.name)
        touched = apply_event(cluster, event)
        if touched:
            store.remove_node(pod_nid)
            scorer.remove_entity(pod_nid)
        return touched

    if event.kind == "incident_arrival":
        inc_nid = event.name if event.name.startswith("incident:") \
            else f"incident:{event.name}"
        svc = event.payload["service"]
        pods = cluster.list_pods(event.namespace, svc)
        evidence = [ids.pod_id(p.namespace, p.name)
                    for p in pods[:event.payload.get("max_evidence", 5)]]
        evidence.append(ids.service_id(event.namespace, svc))
        store.upsert_entities([GraphEntity(
            id=inc_nid, type="Incident",
            properties={"severity": "high", "service": svc,
                        "namespace": event.namespace})])
        store.upsert_relations([
            GraphRelation(source_id=inc_nid, target_id=eid,
                          relation_type="AFFECTS")
            for eid in evidence if store.get_node(eid) is not None])
        scorer.add_incident(inc_nid, [
            eid for eid in evidence if store.get_node(eid) is not None])
        return [inc_nid]

    if event.kind == "incident_close":
        inc_nid = event.name if event.name.startswith("incident:") \
            else f"incident:{event.name}"
        if store.get_node(inc_nid) is None:
            return []
        scorer.close_incident(inc_nid)
        store.cleanup_incident(inc_nid)
        return [inc_nid]

    # mutate-in-place kinds
    touched = apply_event(cluster, event)
    sync_touched_to_store(cluster, store, touched)
    scorer.update_nodes(touched)
    return touched


def sync_touched_to_store(cluster: FakeCluster, store, touched: list[str]) -> None:
    """Propagate mutated cluster state onto the graph-store node property
    bags so feature re-extraction sees the new values (the kube-state sync
    delta path; full sync is graph.topology_sync)."""
    for nid in touched:
        kind, rest = nid.split(":", 1)
        node = store.get_node(nid)
        if node is None:
            continue
        if kind == "pod":
            ns, name = rest.split(":", 1)
            p = cluster.pods.get(f"{ns}/{name}")
            if p is not None:
                from ..collectors.kubernetes import pod_detail
                node_obj = store._nodes[nid]  # in-place property update
                node_obj.properties.update(
                    waiting_reason=p.waiting_reason,
                    terminated_reason=p.terminated_reason,
                    restart_count=p.restart_count, ready=p.ready,
                    not_ready_seconds=p.not_ready_seconds, phase=p.phase,
                    # keep the review-surface detail coherent with the
                    # scalars: graph-API consumers read node properties,
                    # and a churned pod must not show pre-churn container
                    # state next to post-churn scalars
                    **pod_detail(p))
        elif kind == "service":
            ns, name = rest.split(":", 1)
            m = cluster.metrics.get(f"{ns}/{name}")
            if m is not None:
                store._nodes[nid].properties.update(
                    memory_usage_high=m.memory_pct > 90,
                    latency_high=m.p99_latency_s > 1.0)
        elif kind == "deployment":
            ns, name = rest.split(":", 1)
            d = cluster.deployments.get(f"{ns}/{name}")
            if d is not None:
                store._nodes[nid].properties.update(
                    revision=d.revision,
                    is_recent_change=True,
                    changed_at=d.changed_at.isoformat() if d.changed_at else None)
