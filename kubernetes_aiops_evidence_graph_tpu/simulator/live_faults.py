"""Live fault injection — failing workloads applied to a real cluster.

Parity with the reference IncidentSimulator (incident_simulator.py:15-267):
four fault scenarios as K8s manifests (crashloop, oom, imagepull, slowapp),
delete-then-create idempotency, and the ``simulator=kaeg-test`` label so
``cleanup`` can find everything it created. The hermetic analog lives in
scenarios.py/cluster.py; this module is the live-cluster path, sharing the
LiveClusterBackend transport (bearer-token K8s API over stdlib HTTP).
"""
from __future__ import annotations

import http.client
import json
import urllib.request
from typing import Any

LABEL_KEY = "simulator"
LABEL_VALUE = "kaeg-test"


def _labels(name: str) -> dict[str, str]:
    return {"app": name, LABEL_KEY: LABEL_VALUE}


def _deployment(name: str, namespace: str, containers: list[dict],
                replicas: int = 1) -> dict:
    return {
        "apiVersion": "apps/v1",
        "kind": "Deployment",
        "metadata": {"name": name, "namespace": namespace,
                     "labels": _labels(name)},
        "spec": {
            "replicas": replicas,
            "selector": {"matchLabels": {"app": name}},
            "template": {
                "metadata": {"labels": _labels(name)},
                "spec": {"containers": containers},
            },
        },
    }


def manifests(scenario: str, namespace: str) -> list[dict]:
    """Manifests per fault scenario (reference incident_simulator.py:15-160)."""
    if scenario == "crashloop":
        return [_deployment("kaeg-sim-crashloop", namespace, [{
            "name": "app", "image": "busybox:1.36",
            "command": ["sh", "-c", "echo boot failed; exit 1"],
        }])]
    if scenario == "oom":
        return [_deployment("kaeg-sim-oom", namespace, [{
            "name": "app", "image": "python:3.11-alpine",
            "command": ["python", "-c",
                        "b=[];\nimport time\n"
                        "while True: b.append(bytearray(16*1024*1024)); time.sleep(0.2)"],
            "resources": {"limits": {"memory": "64Mi"},
                          "requests": {"memory": "32Mi"}},
        }])]
    if scenario == "imagepull":
        return [_deployment("kaeg-sim-imagepull", namespace, [{
            "name": "app",
            "image": "registry.invalid/nonexistent/image:latest",
        }])]
    if scenario == "slowapp":
        name = "kaeg-sim-slowapp"
        server = (
            "import http.server, random, time\n"
            "class H(http.server.BaseHTTPRequestHandler):\n"
            "    def do_GET(self):\n"
            "        time.sleep(random.uniform(1, 5))\n"
            "        code = 500 if random.random() < 0.3 else 200\n"
            "        self.send_response(code); self.end_headers()\n"
            "http.server.HTTPServer(('', 8080), H).serve_forever()\n")
        return [
            _deployment(name, namespace, [{
                "name": "app", "image": "python:3.11-alpine",
                "command": ["python", "-c", server],
                "ports": [{"containerPort": 8080}],
            }], replicas=2),
            {
                "apiVersion": "v1", "kind": "Service",
                "metadata": {"name": name, "namespace": namespace,
                             "labels": _labels(name)},
                "spec": {"selector": {"app": name},
                         "ports": [{"port": 80, "targetPort": 8080}]},
            },
        ]
    raise ValueError(f"unknown live scenario {scenario!r} "
                     "(crashloop|oom|imagepull|slowapp)")


class LiveFaultInjector:
    """Applies/removes fault manifests through the K8s API."""

    def __init__(self, backend: Any) -> None:
        # backend: LiveClusterBackend (reuses its URL/token/TLS context)
        self.backend = backend

    def _collection(self, manifest: dict) -> str:
        ns = manifest["metadata"]["namespace"]
        if manifest["kind"] == "Deployment":
            return f"/apis/apps/v1/namespaces/{ns}/deployments"
        if manifest["kind"] == "Service":
            return f"/api/v1/namespaces/{ns}/services"
        raise ValueError(f"unsupported kind {manifest['kind']}")

    def _request(self, method: str, path: str, payload: dict | None = None) -> bool:
        b = self.backend
        req = urllib.request.Request(
            b.k8s_url + path, method=method,
            data=json.dumps(payload).encode() if payload is not None else None)
        if b._token:
            req.add_header("Authorization", f"Bearer {b._token}")
        if payload is not None:
            req.add_header("Content-Type", "application/json")
        try:
            with urllib.request.urlopen(req, timeout=b.timeout_s,
                                        context=b._ctx) as resp:
                return 200 <= resp.status < 300
        except (OSError, http.client.HTTPException):
            return False

    def create(self, scenario: str, namespace: str = "default") -> list[str]:
        """Delete-then-create each manifest (idempotent,
        reference incident_simulator.py:203-231)."""
        created = []
        for m in manifests(scenario, namespace):
            coll = self._collection(m)
            self._request("DELETE", f"{coll}/{m['metadata']['name']}")
            if self._request("POST", coll, m):
                created.append(f"{m['kind']}/{m['metadata']['name']}")
        return created

    def cleanup(self, namespace: str = "default") -> list[str]:
        """Remove everything labeled simulator=kaeg-test
        (reference incident_simulator.py:239-267)."""
        removed = []
        selector = f"{LABEL_KEY}={LABEL_VALUE}"
        for coll, kind in (
            (f"/apis/apps/v1/namespaces/{namespace}/deployments", "Deployment"),
            (f"/api/v1/namespaces/{namespace}/services", "Service"),
        ):
            try:
                data = self.backend._get(self.backend.k8s_url, coll,
                                         {"labelSelector": selector}, bearer=True)
            except (OSError, ValueError, http.client.HTTPException):
                continue
            for item in data.get("items", []):
                name = item["metadata"]["name"]
                if self._request("DELETE", f"{coll}/{name}"):
                    removed.append(f"{kind}/{name}")
        return removed

    def list_injected(self, namespace: str = "default") -> list[str]:
        out = []
        selector = f"{LABEL_KEY}={LABEL_VALUE}"
        for coll, kind in (
            (f"/apis/apps/v1/namespaces/{namespace}/deployments", "Deployment"),
            (f"/api/v1/namespaces/{namespace}/services", "Service"),
        ):
            try:
                data = self.backend._get(self.backend.k8s_url, coll,
                                         {"labelSelector": selector}, bearer=True)
            except (OSError, ValueError, http.client.HTTPException):
                continue
            out += [f"{kind}/{i['metadata']['name']}" for i in data.get("items", [])]
        return out
