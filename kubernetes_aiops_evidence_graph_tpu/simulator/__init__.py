from .cluster import FakeCluster
from .scenarios import SCENARIOS, Scenario, inject
from .topology import generate_cluster

__all__ = ["FakeCluster", "SCENARIOS", "Scenario", "inject", "generate_cluster"]
