"""Fault-injection scenarios.

The reference ships 4 live-cluster scenarios (incident_simulator.py:15-171:
crashloop, oom, imagepull, slowapp). Here each scenario is a deterministic
mutation of FakeCluster state, and the set is widened to 10 so every
diagnosis rule in the shared ruleset has at least one scenario that should
make it the top-1 hypothesis — the ground truth for RCA accuracy.
"""
from __future__ import annotations

from dataclasses import dataclass
from datetime import timedelta
from typing import Callable

import numpy as np

from ..models import Incident, IncidentSource, Severity
from ..utils.hashing import alert_fingerprint
from .cluster import FakeCluster

_ERROR_LINE = "ERROR worker crashed: exit status 1"
_NETWORK_LINES = [
    "ERROR dial tcp 10.0.0.7:5432: connection refused",
    "WARN upstream request timeout after 5s",
    "ERROR read tcp: connection reset by peer",
]


@dataclass(frozen=True)
class Scenario:
    name: str
    alertname: str
    severity: Severity
    expected_rule: str            # ground-truth top-1 rule id
    apply: Callable[[FakeCluster, str, np.random.Generator], None]
    description: str = ""


def _pods(cluster: FakeCluster, target: str):
    ns, dname = target.split("/", 1)
    return ns, dname, cluster.list_pods(ns, dname)


def _burst_logs(cluster: FakeCluster, ns: str, pods, lines: list[str], repeat: int = 8):
    for p in pods:
        cluster.set_logs(ns, p.name, lines * repeat)


def _apply_crashloop_deploy(cluster: FakeCluster, target: str, rng) -> None:
    ns, dname, pods = _pods(cluster, target)
    d = cluster.deployments[target]
    d.revision += 1
    d.prev_image = d.image
    d.image = d.image.rsplit(":", 1)[0] + f":v{d.revision}"
    d.changed_at = cluster.now - timedelta(minutes=10)
    d.ready_replicas = 0
    for p in pods:
        p.phase = "Running"
        p.ready = False
        p.waiting_reason = "CrashLoopBackOff"
        p.restart_count = int(rng.integers(4, 12))
        cluster.add_event(ns, p.name, "BackOff", "Back-off restarting failed container")
    _burst_logs(cluster, ns, pods, [_ERROR_LINE, "CRITICAL panic: nil config"])


def _apply_crashloop(cluster: FakeCluster, target: str, rng) -> None:
    ns, dname, pods = _pods(cluster, target)
    cluster.deployments[target].ready_replicas = 0
    for p in pods:
        p.ready = False
        p.waiting_reason = "CrashLoopBackOff"
        p.restart_count = int(rng.integers(4, 12))
        cluster.add_event(ns, p.name, "BackOff", "Back-off restarting failed container")
    _burst_logs(cluster, ns, pods, [_ERROR_LINE])


def _apply_oom(cluster: FakeCluster, target: str, rng) -> None:
    ns, dname, pods = _pods(cluster, target)
    for p in pods:
        p.terminated_reason = "OOMKilled"
        p.restart_count = int(rng.integers(2, 8))
        cluster.add_event(ns, p.name, "OOMKilling", "Memory cgroup out of memory")
    m = cluster.service_metrics(ns, dname)
    m.memory_pct = 99.0
    m.oom_events = float(len(pods))
    _burst_logs(cluster, ns, pods, ["CRITICAL out of memory", _ERROR_LINE])


def _apply_oom_pressure(cluster: FakeCluster, target: str, rng) -> None:
    ns, dname, _ = _pods(cluster, target)
    m = cluster.service_metrics(ns, dname)
    m.memory_pct = 94.0


def _apply_imagepull(cluster: FakeCluster, target: str, rng) -> None:
    ns, dname, pods = _pods(cluster, target)
    d = cluster.deployments[target]
    d.ready_replicas = 0
    for p in pods:
        p.phase = "Pending"
        p.ready = False
        p.waiting_reason = "ImagePullBackOff"
        cluster.add_event(ns, p.name, "Failed", "Failed to pull image")


def _apply_node_pressure(cluster: FakeCluster, target: str, rng) -> None:
    ns, dname, pods = _pods(cluster, target)
    if not pods:
        return
    node_name = pods[0].node
    node = cluster.nodes[node_name]
    node.conditions["Ready"] = "False"
    node.conditions["MemoryPressure"] = "True"
    # co-locate the target's pods on the sick node: >= 2 problem pods there,
    # with not_ready below the 300s probe-rule threshold so only the node
    # rule fires
    for p in pods:
        p.node = node_name
        p.ready = False
        p.not_ready_seconds = 120.0
        p.restart_count = int(rng.integers(4, 9))
        cluster.add_event(ns, p.name, "NodeNotReady", "Node is not ready")


def _apply_hpa_maxed(cluster: FakeCluster, target: str, rng) -> None:
    ns, dname, pods = _pods(cluster, target)
    hpa = cluster.hpas.get(target)
    if hpa is None:
        from .cluster import HPAState
        hpa = cluster.hpas[target] = HPAState(name=dname, namespace=ns, deployment=dname)
    hpa.current_replicas = hpa.max_replicas
    hpa.at_max = True
    m = cluster.service_metrics(ns, dname)
    m.p99_latency_s = 4.2
    m.hpa_at_max = 1.0


def _apply_probe_failure(cluster: FakeCluster, target: str, rng) -> None:
    ns, dname, pods = _pods(cluster, target)
    cluster.deployments[target].ready_replicas = 0
    for p in pods:
        p.ready = False
        p.not_ready_seconds = 600.0
        p.readiness_probe_failing = True
        cluster.add_event(ns, p.name, "Unhealthy", "Readiness probe failed: HTTP 503")


def _apply_config_error(cluster: FakeCluster, target: str, rng) -> None:
    ns, dname, pods = _pods(cluster, target)
    cmap_key = f"{ns}/{dname}-config"
    if cmap_key not in cluster.configmaps:
        from .cluster import ConfigMapState
        cluster.configmaps[cmap_key] = ConfigMapState(name=f"{dname}-config", namespace=ns,
                                                      mounted_by=[dname])
    cluster.configmaps[cmap_key].changed_at = cluster.now - timedelta(minutes=5)
    for p in pods:
        p.ready = False
        p.terminated_reason = "CreateContainerConfigError"
        cluster.add_event(ns, p.name, "Failed", "Error: configmap key not found")


def _apply_network(cluster: FakeCluster, target: str, rng) -> None:
    ns, dname, pods = _pods(cluster, target)
    m = cluster.service_metrics(ns, dname)
    m.error_rate = 0.31
    _burst_logs(cluster, ns, pods, _NETWORK_LINES, repeat=10)


SCENARIOS: dict[str, Scenario] = {
    s.name: s for s in (
        Scenario("crashloop_deploy", "PodCrashLooping", Severity.CRITICAL,
                 "crashloop_recent_deploy", _apply_crashloop_deploy,
                 "crashloop right after a rollout (reference crashloop + deploy-diff)"),
        Scenario("crashloop", "PodCrashLooping", Severity.CRITICAL,
                 "crashloop_no_change", _apply_crashloop,
                 "crashloop with no recent change (reference crashloop scenario)"),
        Scenario("oom", "ContainerOOMKilled", Severity.CRITICAL,
                 "oom_killed", _apply_oom,
                 "container OOMKilled (reference oom scenario)"),
        Scenario("oom_pressure", "HighMemory", Severity.HIGH,
                 "oom_high_memory", _apply_oom_pressure,
                 "memory >90% of limit, no kill yet"),
        Scenario("imagepull", "PodImagePullBackOff", Severity.HIGH,
                 "image_pull_failure", _apply_imagepull,
                 "unpullable image (reference imagepull scenario)"),
        Scenario("node_pressure", "NodeNotReady", Severity.CRITICAL,
                 "node_failure_isolated", _apply_node_pressure,
                 "unhealthy node taking down co-located pods"),
        Scenario("hpa_maxed", "HPAAtMax", Severity.HIGH,
                 "hpa_maxed", _apply_hpa_maxed,
                 "autoscaler pegged at max with high latency (reference slowapp analog)"),
        Scenario("probe_failure", "PodNotReady", Severity.HIGH,
                 "readiness_probe_failing", _apply_probe_failure,
                 "pods failing readiness probes"),
        Scenario("config_error", "PodCrashLooping", Severity.HIGH,
                 "config_error", _apply_config_error,
                 "bad configmap reference"),
        Scenario("network", "HighErrorRate", Severity.HIGH,
                 "network_error", _apply_network,
                 "connection refused/timeout storm (reference slowapp analog)"),
    )
}


def inject(
    cluster: FakeCluster,
    scenario_name: str,
    target: str,
    rng: np.random.Generator | None = None,
) -> Incident:
    """Apply a scenario to a target "namespace/deployment" and return the
    incident an alert webhook would have created for it."""
    scenario = SCENARIOS[scenario_name]
    rng = rng or np.random.default_rng(cluster.seed)
    scenario.apply(cluster, target, rng)
    ns, dname = target.split("/", 1)
    fp = alert_fingerprint("alertmanager", scenario.alertname, ns, dname)
    return Incident(
        fingerprint=fp,
        title=f"{scenario.alertname}: {dname}",
        description=scenario.description,
        severity=scenario.severity,
        source=IncidentSource.ALERTMANAGER,
        cluster="sim",
        namespace=ns,
        service=dname,
        labels={"alertname": scenario.alertname, "namespace": ns, "service": dname,
                "scenario": scenario.name},
        started_at=cluster.now,
    )
