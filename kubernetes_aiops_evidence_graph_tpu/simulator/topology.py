"""Synthetic cluster topology generation.

Deterministic, seedable generator for BASELINE.json's scale ladder
(200 pods → 50k nodes): namespaces, nodes, deployments (with services,
HPAs, configmaps), pods spread over nodes, and a CALLS mesh between
services. All randomness flows from one numpy Generator so identical seeds
reproduce identical clusters on every host.
"""
from __future__ import annotations

import numpy as np

from ..utils.timeutils import utcnow
from .cluster import (
    ConfigMapState,
    DeploymentState,
    FakeCluster,
    HPAState,
    NodeState,
    PodState,
    ServiceState,
)


def generate_cluster(
    num_pods: int = 200,
    seed: int = 0,
    pods_per_deployment: int = 4,
    pods_per_node: int = 12,
    namespaces: int | None = None,
    calls_per_service: float = 1.5,
) -> FakeCluster:
    rng = np.random.default_rng(seed)
    cluster = FakeCluster(seed=seed)
    cluster.now = utcnow()

    n_deploys = max(1, num_pods // pods_per_deployment)
    n_nodes = max(1, num_pods // pods_per_node)
    n_ns = namespaces if namespaces is not None else max(1, min(50, n_deploys // 8))

    ns_names = [f"ns-{i}" for i in range(n_ns)]
    ns_names[0] = "default"

    for i in range(n_nodes):
        name = f"node-{i}"
        cluster.nodes[name] = NodeState(name=name)

    pod_budget = num_pods
    deploy_index = 0
    while pod_budget > 0 and deploy_index < n_deploys:
        ns = ns_names[deploy_index % n_ns]
        dname = f"svc-{deploy_index}"
        replicas = int(min(pod_budget, max(1, rng.poisson(pods_per_deployment))))
        pod_budget -= replicas
        key = f"{ns}/{dname}"
        cluster.deployments[key] = DeploymentState(
            name=dname, namespace=ns, service=dname,
            replicas=replicas, ready_replicas=replicas,
        )
        cluster.services[key] = ServiceState(name=dname, namespace=ns, deployment=dname)
        if rng.random() < 0.3:
            cluster.hpas[key] = HPAState(
                name=dname, namespace=ns, deployment=dname,
                max_replicas=replicas + int(rng.integers(1, 5)),
                current_replicas=replicas,
            )
        if rng.random() < 0.5:
            cluster.configmaps[f"{ns}/{dname}-config"] = ConfigMapState(
                name=f"{dname}-config", namespace=ns, mounted_by=[dname],
            )
        for r in range(replicas):
            suffix = rng.integers(0, 16**5)
            pname = f"{dname}-{suffix:05x}-{r}"
            node = f"node-{int(rng.integers(0, n_nodes))}"
            cluster.pods[f"{ns}/{pname}"] = PodState(
                name=pname, namespace=ns, deployment=dname, service=dname,
                node=node, started_at=cluster.now,
            )
        deploy_index += 1

    # CALLS mesh: each service calls a few others (neo4j.py:254-278 analog)
    deploy_keys = sorted(cluster.services)
    for key in deploy_keys:
        svc = cluster.services[key]
        n_calls = int(rng.poisson(calls_per_service))
        for _ in range(n_calls):
            other = deploy_keys[int(rng.integers(0, len(deploy_keys)))]
            o = cluster.services[other]
            if o.name != svc.name and o.namespace == svc.namespace and o.name not in svc.calls:
                svc.calls.append(o.name)

    return cluster
