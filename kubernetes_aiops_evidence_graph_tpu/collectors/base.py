"""Collector base — template method with timing + error isolation.

Parity with the reference BaseCollector (src/services/collectors/base.py:33-111):
the evidence window is ``incident.started_at - evidence_time_window_minutes``
→ now; ``run()`` never raises — failures come back as an unsuccessful
CollectorResult; ``make_evidence`` stamps incident/namespace/window.
"""
from __future__ import annotations

import time
from datetime import datetime, timedelta
from typing import Any

from ..config import Settings, get_settings
from ..models import CollectorResult, Evidence, EvidenceSource, EvidenceType, Incident
from ..observability_hooks import observe_collector


class BaseCollector:
    name = "base"
    source = EvidenceSource.SIMULATOR

    def __init__(self, backend: Any, settings: Settings | None = None) -> None:
        self.backend = backend
        self.settings = settings or get_settings()

    def window(self, incident: Incident, now: datetime) -> tuple[datetime, datetime]:
        start = incident.started_at - timedelta(minutes=self.settings.evidence_time_window_minutes)
        return start, now

    def run(self, incident: Incident) -> CollectorResult:
        t0 = time.perf_counter()
        try:
            result = self.collect(incident)
            result.collector_name = self.name
        except Exception as exc:  # graft-audit: allow[broad-except] collector isolation (base.py:71-86): one bad collector never sinks the evidence pass
            result = CollectorResult(collector_name=self.name, success=False, errors=[str(exc)])
        result.duration_seconds = time.perf_counter() - t0
        observe_collector(self.name, result)
        return result

    def collect(self, incident: Incident) -> CollectorResult:
        raise NotImplementedError

    def make_evidence(
        self,
        incident: Incident,
        evidence_type: EvidenceType,
        entity_name: str,
        data: dict,
        signal_strength: float = 0.5,
        is_anomaly: bool = False,
        namespace: str | None = None,
        summary: str | None = None,
    ) -> Evidence:
        start, end = self.window(incident, getattr(self.backend, "now", incident.started_at))
        return Evidence(
            incident_id=incident.id,
            evidence_type=evidence_type,
            source=self.source,
            entity_name=entity_name,
            entity_namespace=namespace or incident.namespace,
            data=data,
            summary=summary,
            signal_strength=signal_strength,
            is_anomaly=is_anomaly,
            time_window_start=start,
            time_window_end=end,
        )
