"""Kubernetes state collector.

Parity with the reference KubernetesCollector (kubernetes_collector.py:50-625):
five sub-collections (pods, deployments, events, nodes, HPAs), the same
signal-strength heuristic (:269-285 — crash/image/OOM reasons 0.95,
restarts>3 0.8, non-Running 0.7, else 0.3), unhealthy-only node emission
(:504-557), and the Pod/Deployment/Node/Service entity + SCHEDULED_ON/OWNS/
SELECTS/AFFECTS relation emission (:296-313). Queries go through the
ClusterBackend interface instead of the kubernetes client, so the same code
runs against FakeCluster or a real API server.
"""
from __future__ import annotations

from ..graph import ids
from ..models import (
    CollectorResult,
    EvidenceSource,
    EvidenceType,
    GraphEntity,
    GraphRelation,
    Incident,
)
from .base import BaseCollector

_CRITICAL_WAITING = {"CrashLoopBackOff", "ImagePullBackOff", "ErrImagePull", "ImageInspectError"}
_CRITICAL_EVENTS = {"FailedScheduling", "FailedMount", "BackOff", "Unhealthy", "Failed",
                    "OOMKilling", "NodeNotReady"}


def pod_signal_strength(waiting: str | None, terminated: str | None,
                        restarts: int, phase: str) -> float:
    """Reference heuristic (kubernetes_collector.py:269-285)."""
    if (waiting in _CRITICAL_WAITING) or terminated == "OOMKilled":
        return 0.95
    if restarts > 3:
        return 0.8
    if phase != "Running":
        return 0.7
    return 0.3


def pod_detail(p) -> dict:
    """Review-surface pod detail (reference kubernetes_collector.py:194-267
    payload shape): per-container conditions / state / last-state /
    resources. Backends that read the wire (collectors/live.py) attach the
    real data on PodState; for backends that only track scalars (the fake
    cluster) this synthesizes the equivalent one-container view, so
    runbooks, tickets and graph-API consumers see the same payload shape
    either way (VERDICT r4 item 7)."""
    if p.container_statuses is not None:
        return {"conditions": p.conditions or [],
                "container_statuses": p.container_statuses,
                "resources": p.resources or {},
                "labels": p.labels or {}}
    status: dict = {"name": "app", "ready": p.ready,
                    "restart_count": p.restart_count}
    if p.waiting_reason:
        status["waiting"] = {"reason": p.waiting_reason, "message": None}
    if p.terminated_reason:
        # scalar state keeps only the reason; a restarting container
        # reports it as last-state (the live path distinguishes both)
        status["last_terminated"] = {"reason": p.terminated_reason,
                                     "exit_code": 137
                                     if p.terminated_reason == "OOMKilled"
                                     else 1}
    ready_cond = {"type": "Ready", "status": "True" if p.ready else "False",
                  "reason": None}
    return {"conditions": [ready_cond], "container_statuses": [status],
            "resources": {}, "labels": {"app": p.service}}


class KubernetesCollector(BaseCollector):
    name = "kubernetes"
    source = EvidenceSource.KUBERNETES_API

    def collect(self, incident: Incident) -> CollectorResult:
        result = CollectorResult(collector_name=self.name)
        ns, svc = incident.namespace, incident.service
        inc_node = ids.incident_id(str(incident.id))

        self._collect_pods(incident, ns, svc, inc_node, result)
        self._collect_deployments(incident, ns, svc, inc_node, result)
        self._collect_events(incident, ns, result)
        self._collect_nodes(incident, result)
        self._collect_hpas(incident, ns, svc, result)
        return result

    def _collect_pods(self, incident, ns, svc, inc_node, result) -> None:
        for p in self.backend.list_pods(ns, svc):
            strength = pod_signal_strength(p.waiting_reason, p.terminated_reason,
                                           p.restart_count, p.phase)
            data = {
                "waiting_reason": p.waiting_reason,
                "terminated_reason": p.terminated_reason,
                "restart_count": p.restart_count,
                "ready": p.ready,
                "not_ready_seconds": p.not_ready_seconds,
                "readiness_probe_failing": p.readiness_probe_failing,
                "phase": p.phase,
                "node": p.node,
                # reference contract (kubernetes_collector.py:162):
                # created_at is metadata.creationTimestamp, NOT
                # status.startTime — they differ for pending/late-started
                # pods. The fake cluster tracks no separate creation time,
                # so started_at stands in there (creation == start in sim).
                "created_at": (p.creation_ts or p.started_at).isoformat()
                if (p.creation_ts or p.started_at) else None,
                **pod_detail(p),
            }
            result.evidence.append(self.make_evidence(
                incident, EvidenceType.KUBERNETES_POD, p.name, data,
                signal_strength=strength, is_anomaly=strength >= 0.7, namespace=ns,
            ))
            pod_node = ids.pod_id(ns, p.name)
            result.entities.append(GraphEntity(id=pod_node, type="Pod", properties=data))
            result.entities.append(GraphEntity(id=ids.node_id(p.node), type="Node"))
            result.relations.append(GraphRelation(
                source_id=pod_node, target_id=ids.node_id(p.node), relation_type="SCHEDULED_ON"))
            result.relations.append(GraphRelation(
                source_id=ids.deployment_id(ns, p.deployment), target_id=pod_node,
                relation_type="OWNS"))
            result.relations.append(GraphRelation(
                source_id=ids.service_id(ns, p.service), target_id=pod_node,
                relation_type="SELECTS"))
            result.relations.append(GraphRelation(
                source_id=inc_node, target_id=pod_node, relation_type="AFFECTS"))

    def _collect_deployments(self, incident, ns, svc, inc_node, result) -> None:
        for d in self.backend.list_deployments(ns, svc):
            unavailable = max(0, d.replicas - d.ready_replicas)
            data = {
                "replicas": d.replicas,
                "ready_replicas": d.ready_replicas,
                "unavailable_replicas": unavailable,
                "revision": d.revision,
                "image": d.image,
            }
            result.evidence.append(self.make_evidence(
                incident, EvidenceType.KUBERNETES_DEPLOYMENT, d.name, data,
                signal_strength=0.8 if unavailable else 0.3,  # :406-417
                is_anomaly=unavailable > 0, namespace=ns,
            ))
            dep_node = ids.deployment_id(ns, d.name)
            result.entities.append(GraphEntity(id=dep_node, type="Deployment", properties=data))
            result.entities.append(GraphEntity(
                id=ids.service_id(ns, d.service), type="Service",
                properties={"name": d.service, "namespace": ns}))
            result.relations.append(GraphRelation(
                source_id=inc_node, target_id=dep_node, relation_type="AFFECTS"))

    def _collect_events(self, incident, ns, result) -> None:
        start, _ = self.window(incident, self.backend.now)
        for e in self.backend.list_events(ns, start):
            if e.type != "Warning":
                continue
            strength = 0.9 if e.reason in _CRITICAL_EVENTS else 0.5  # :476-482
            result.evidence.append(self.make_evidence(
                incident, EvidenceType.KUBERNETES_EVENT, e.involved_object,
                {"reason": e.reason, "message": e.message, "type": e.type},
                signal_strength=strength, is_anomaly=strength >= 0.9, namespace=ns,
            ))

    def _collect_nodes(self, incident, result) -> None:
        for n in self.backend.list_nodes():
            ready = n.conditions.get("Ready", "True")
            pressures = {
                k: v for k, v in n.conditions.items()
                if k in ("MemoryPressure", "DiskPressure", "PIDPressure",
                         "NetworkUnavailable", "Unschedulable")
                and v == "True"
            }
            if ready == "True" and not pressures:
                continue  # only unhealthy/cordoned nodes are evidence (:504-557)
            data = {"name": n.name, "conditions": {k: {"status": v} for k, v in n.conditions.items()}}
            result.evidence.append(self.make_evidence(
                incident, EvidenceType.KUBERNETES_NODE, n.name, data,
                signal_strength=0.85, is_anomaly=True, namespace=incident.namespace,
            ))
            result.entities.append(GraphEntity(id=ids.node_id(n.name), type="Node", properties=data))

    def _collect_hpas(self, incident, ns, svc, result) -> None:
        for h in self.backend.list_hpas(ns, svc):
            at_max = h.at_max or h.current_replicas >= h.max_replicas
            data = {
                "at_max": at_max,
                "current_replicas": h.current_replicas,
                "max_replicas": h.max_replicas,
            }
            result.evidence.append(self.make_evidence(
                incident, EvidenceType.KUBERNETES_HPA, h.name, data,
                signal_strength=0.8 if at_max else 0.3, is_anomaly=at_max, namespace=ns,  # :577-625
            ))
            result.entities.append(GraphEntity(
                id=ids.hpa_id(ns, h.name), type="HPA", properties=data))
            result.relations.append(GraphRelation(
                source_id=ids.hpa_id(ns, h.name),
                target_id=ids.deployment_id(ns, h.deployment),
                relation_type="OWNS"))
