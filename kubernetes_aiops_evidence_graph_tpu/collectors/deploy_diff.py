"""Deploy-diff collector — recent rollout / image / config change evidence.

Parity with the reference DeployDiffCollector (deploy_diff_collector.py:49-458):
rollout recency vs a 30-minute window → DEPLOY_CHANGE (signal 0.95 when
recent), top-2 revision image comparison → IMAGE_CHANGE (0.85), configmap
changes inside the evidence window → CONFIG_CHANGE (0.6); recent changes
emit a ChangeEvent entity plus HAS_RECENT_CHANGE / CORRELATES_WITH
relations (:233-268).
"""
from __future__ import annotations

from datetime import timedelta

from ..graph import ids
from ..models import (
    CollectorResult,
    EvidenceSource,
    EvidenceType,
    GraphEntity,
    GraphRelation,
    Incident,
)
from ..rca.ruleset import RECENT_DEPLOY_WINDOW_MIN
from .base import BaseCollector


class DeployDiffCollector(BaseCollector):
    name = "deploy_diff"
    source = EvidenceSource.KUBERNETES_API

    def collect(self, incident: Incident) -> CollectorResult:
        result = CollectorResult(collector_name=self.name)
        ns = incident.namespace
        now = self.backend.now
        inc_node = ids.incident_id(str(incident.id))
        recent_cutoff = now - timedelta(minutes=RECENT_DEPLOY_WINDOW_MIN)

        for d in self.backend.list_deployments(ns, incident.service):
            history = self.backend.rollout_history(ns, d.name)
            if not history:
                continue
            head = history[0]
            changed_at = head.get("changed_at")
            is_recent = changed_at is not None and changed_at >= recent_cutoff
            data = {
                "deployment": d.name,
                "revision": head["revision"],
                "image": head["image"],
                "is_recent_change": is_recent,
                "changed_at": changed_at.isoformat() if changed_at else None,
            }
            result.evidence.append(self.make_evidence(
                incident, EvidenceType.DEPLOY_CHANGE, d.name, data,
                signal_strength=0.95 if is_recent else 0.2,  # :93-215
                is_anomaly=is_recent,
            ))
            if is_recent:
                change_node = ids.change_id(ns, d.name, head["revision"])
                dep_node = ids.deployment_id(ns, d.name)
                result.entities.append(GraphEntity(
                    id=change_node, type="ChangeEvent",
                    properties={
                        "namespace": ns, "deployment": d.name,
                        "revision": head["revision"],
                        "changed_at": changed_at.isoformat(),
                        "is_recent_change": True,
                    }))
                result.relations.append(GraphRelation(
                    source_id=dep_node, target_id=change_node,
                    relation_type="HAS_RECENT_CHANGE"))
                result.relations.append(GraphRelation(
                    source_id=inc_node, target_id=change_node,
                    relation_type="CORRELATES_WITH"))

            # image diff between top-2 revisions (:270-394)
            if len(history) >= 2 and history[0]["image"] != history[1]["image"]:
                result.evidence.append(self.make_evidence(
                    incident, EvidenceType.IMAGE_CHANGE, d.name,
                    {
                        "deployment": d.name,
                        "image_changed": True,
                        "old_image": history[1]["image"],
                        "new_image": history[0]["image"],
                    },
                    signal_strength=0.85, is_anomaly=True,
                ))

        # configmap changes within the evidence window (:396-458)
        window_start, _ = self.window(incident, now)
        for c in self.backend.list_configmaps(ns):
            if c.changed_at is not None and c.changed_at >= window_start:
                result.evidence.append(self.make_evidence(
                    incident, EvidenceType.CONFIG_CHANGE, c.name,
                    {
                        "configmap": c.name,
                        "config_changed": True,
                        "changed_at": c.changed_at.isoformat(),
                        "mounted_by": list(c.mounted_by),
                    },
                    signal_strength=0.6, is_anomaly=True,
                ))
        return result
