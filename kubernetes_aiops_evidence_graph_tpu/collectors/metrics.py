"""Metrics collector — alert-driven PromQL category selection over
time-series windows.

Parity with the reference MetricsCollector (metrics_collector.py:31-329):
loads the promql library, selects categories by alertname keywords
(:78-99), queries the backend per named query over the evidence window
(``query_range``, step = max(15, range/100), :161-185), downsamples to
≤``max_metric_points`` and keeps last-50 values + min/max/avg/current
(:195-245), and applies the per-family anomaly thresholds (:247-329) to
set signal strength. Unlike the reference — which collects the series but
thresholds only the final sample — the threshold applies to the family's
windowed statistic (utils/metricseries.EVAL_STAT), so spikes that receded
and trends racing toward a limit still flip rules. Emits one METRIC_SIGNAL
evidence per query with ``query_name`` / ``current_value`` /
``eval_value`` / ``is_anomalous`` + the stats block — the keys the signal
folds read (rules_engine.py:337-350).
"""
from __future__ import annotations

from pathlib import Path

import yaml

from ..models import CollectorResult, EvidenceSource, EvidenceType, Incident
from ..utils.metricseries import (
    EVAL_STAT, downsample, eval_value, series_stats,
)
from ..utils.timeutils import to_epoch_s
from .base import BaseCollector

_QUERIES_PATH = Path(__file__).resolve().parent.parent / "config" / "promql_queries.yaml"

# alertname keyword -> categories (reference :78-99)
_KEYWORD_CATEGORIES = (
    ("crash", ("crashloop", "resource")),
    ("oom", ("oom", "resource")),
    ("memory", ("oom", "resource")),
    ("imagepull", ("deployment",)),
    ("notready", ("node", "deployment")),
    ("node", ("node",)),
    ("hpa", ("hpa", "latency")),
    ("scal", ("hpa", "latency")),
    ("latency", ("latency", "error_rate")),
    ("slow", ("latency", "error_rate")),
    ("error", ("error_rate", "network")),
    ("throttl", ("resource",)),
)
_DEFAULT_CATEGORIES = ("crashloop", "resource", "error_rate")

# query family -> (threshold, predicate description) (reference :247-329)
_THRESHOLDS: dict[str, float] = {
    "pod_restarts": 5.0,
    "error_rate": 0.1,
    "memory_usage_pct": 90.0,
    "latency_p99_seconds": 5.0,
    "cpu_throttle_ratio": 0.5,
    "oom_events": 0.0,      # any OOM is anomalous (strict >)
    "hpa_at_max": 0.5,      # gauge 0/1
}
_STRENGTH: dict[str, float] = {
    "pod_restarts": 0.9,
    "error_rate": 0.9,
    "memory_usage_pct": 0.9,
    "latency_p99_seconds": 0.9,
    "cpu_throttle_ratio": 0.8,
    "oom_events": 0.95,
    "hpa_at_max": 0.8,
}


from functools import lru_cache


@lru_cache(maxsize=1)
def load_query_library() -> dict[str, dict[str, str]]:
    # memoized: the live backend renders a query per metric per collect and
    # must not re-read/re-parse the YAML on the per-query hot path
    with open(_QUERIES_PATH) as fh:
        return yaml.safe_load(fh)


def select_categories(alertname: str) -> list[str]:
    lowered = (alertname or "").lower()
    cats: list[str] = []
    for keyword, categories in _KEYWORD_CATEGORIES:
        if keyword in lowered:
            for c in categories:
                if c not in cats:
                    cats.append(c)
    return cats or list(_DEFAULT_CATEGORIES)


class MetricsCollector(BaseCollector):
    name = "metrics"
    source = EvidenceSource.PROMETHEUS

    def __init__(self, backend, settings=None) -> None:
        super().__init__(backend, settings)
        self.library = load_query_library()

    def _fetch_series(self, incident: Incident,
                      query_name: str) -> list[tuple[float, float]]:
        """Window series from the backend; instant-value fallback when the
        backend predates query_metric_range (single-sample series — stats
        then degenerate to the instant semantics)."""
        start, end = self.window(
            incident, getattr(self.backend, "now", incident.started_at))
        start_s, end_s = to_epoch_s(start), to_epoch_s(end)
        range_fn = getattr(self.backend, "query_metric_range", None)
        if range_fn is not None:
            samples = range_fn(incident.namespace, incident.service,
                               query_name, start_s, end_s)
            if samples:
                return downsample(sorted(samples),
                                  self.settings.max_metric_points)
        value = self.backend.query_metric(
            incident.namespace, incident.service, query_name)
        return [] if value is None else [(end_s, float(value))]

    def collect(self, incident: Incident) -> CollectorResult:
        result = CollectorResult(collector_name=self.name)
        if not incident.service:
            return result
        alertname = incident.labels.get("alertname", incident.title)
        seen: set[str] = set()
        for category in select_categories(alertname):
            for query_name in self.library.get(category, {}):
                if query_name in seen:
                    continue
                seen.add(query_name)
                samples = self._fetch_series(incident, query_name)
                if not samples:
                    continue
                stats = series_stats(samples)
                ev = eval_value(query_name, stats)
                threshold = _THRESHOLDS.get(query_name)
                anomalous = (threshold is not None and ev is not None
                             and ev > threshold)
                result.evidence.append(self.make_evidence(
                    incident, EvidenceType.METRIC_SIGNAL, incident.service,
                    {
                        "query_name": query_name,
                        "category": category,
                        "current_value": float(stats["current_value"]),
                        "eval_value": None if ev is None else float(ev),
                        "eval_stat": EVAL_STAT.get(query_name, "current"),
                        "threshold": threshold,
                        "is_anomalous": anomalous,
                        **{k: stats[k] for k in
                           ("values", "num_points", "min_value", "max_value",
                            "avg_value", "trend_per_min")},
                    },
                    signal_strength=_STRENGTH.get(query_name, 0.5) if anomalous else 0.3,
                    is_anomaly=anomalous,
                ))
        return result
