"""Live cluster backend — the collectors' real-endpoint implementation.

The same ClusterBackend protocol the FakeCluster serves hermetically
(simulator/cluster.py), implemented against a real Kubernetes API server,
Prometheus, and Loki — the trio the reference collectors speak to directly
(kubernetes_collector.py via the kubernetes client; logs_collector.py:80-110
Loki query_range; metrics_collector.py:161-185 Prometheus query_range).

Keeping the seam at the backend (not the collector) means every collector,
the rules engines, and the whole workflow run identically against fake and
live clusters; only this file touches the network. stdlib-only HTTP (this
image has no guaranteed httpx/kubernetes client, and the ingestion edge is
not the hot path).

Auth follows the in-cluster convention: service-account bearer token +
cluster CA from /var/run/secrets/kubernetes.io/serviceaccount, overridable
for out-of-cluster use.
"""
from __future__ import annotations

import http.client
import json
import ssl
import urllib.error
import urllib.parse
import urllib.request
from datetime import datetime
from pathlib import Path
from typing import Any, Optional

from ..config import Settings, get_settings
from ..utils.timeutils import parse_iso, utcnow
from ..simulator.cluster import (
    ConfigMapState,
    DeploymentState,
    EventState,
    HPAState,
    NodeState,
    PodState,
)

_SA_DIR = Path("/var/run/secrets/kubernetes.io/serviceaccount")


def _pod_prefix(service: str) -> str:
    return service


class LiveClusterBackend:
    """ClusterBackend over real K8s API + Prometheus + Loki HTTP."""

    def __init__(
        self,
        settings: Settings | None = None,
        *,
        k8s_url: str | None = None,
        k8s_token: str | None = None,
        k8s_ca_path: str | None = None,
        prometheus_url: str | None = None,
        loki_url: str | None = None,
        timeout_s: float = 10.0,
    ) -> None:
        self.settings = settings or get_settings()
        self.k8s_url = (k8s_url or "https://kubernetes.default.svc").rstrip("/")
        self.prometheus_url = (prometheus_url or self.settings.prometheus_url).rstrip("/")
        self.loki_url = (loki_url or self.settings.loki_url).rstrip("/")
        self.timeout_s = timeout_s
        if k8s_token is None and (_SA_DIR / "token").exists():
            k8s_token = (_SA_DIR / "token").read_text().strip()
        self._token = k8s_token
        ca = k8s_ca_path or (str(_SA_DIR / "ca.crt") if (_SA_DIR / "ca.crt").exists() else None)
        if self.k8s_url.startswith("https"):
            self._ctx: ssl.SSLContext | None = (
                ssl.create_default_context(cafile=ca) if ca else ssl.create_default_context())
        else:
            self._ctx = None
        from ..observability import get_logger
        self._log = get_logger("live_backend")

    @property
    def now(self) -> datetime:
        """Wall clock — the FakeCluster pins this for determinism; live
        backends always answer with real time."""
        return utcnow()

    # -- transport --------------------------------------------------------

    def _get(self, base: str, path: str, params: dict[str, Any] | None = None,
             bearer: bool = False) -> Any:
        url = base + path
        if params:
            url += "?" + urllib.parse.urlencode(params)
        req = urllib.request.Request(url, headers={"Accept": "application/json"})
        if bearer and self._token:
            req.add_header("Authorization", f"Bearer {self._token}")
        with urllib.request.urlopen(req, timeout=self.timeout_s,
                                    context=self._ctx if base == self.k8s_url else None) as resp:
            ctype = (resp.headers.get("Content-Type") or "").split(";")[0].strip()
            body = resp.read()
            # a proxy/login page answering 200 text/html would otherwise
            # surface as an inscrutable JSONDecodeError ten frames deeper
            if ctype and "json" not in ctype:
                raise ValueError(
                    f"non-JSON response from {url}: Content-Type={ctype!r}, "
                    f"body starts {body[:80]!r}")
            return json.loads(body)

    def _k8s(self, path: str, params: dict[str, Any] | None = None) -> Any:
        return self._get(self.k8s_url, path, params, bearer=True)

    # real API servers chunk large collections; a 50k-pod namespace comes
    # back in pages threaded by metadata.continue (an opaque token the
    # client must echo verbatim). The reference's kubernetes client pages
    # transparently; this client must too or big lists silently truncate.
    _LIST_LIMIT = 500

    def _k8s_list(self, path: str,
                  params: dict[str, Any] | None = None) -> list[dict]:
        # A continue token can outlive etcd compaction on a churning
        # cluster; the API server then answers 410 Gone. The official
        # clients relist from scratch once — do the same rather than
        # failing the whole collection mid-listing.
        for attempt in range(2):
            items: list[dict] = []
            page = dict(params or {})
            page["limit"] = self._LIST_LIMIT
            minted = False  # did WE advance past the caller's first page?
            try:
                while True:
                    data = self._k8s(path, page)
                    items.extend(data.get("items") or [])
                    token = (data.get("metadata") or {}).get("continue")
                    if not token:
                        return items
                    page = dict(params or {})
                    page["limit"] = self._LIST_LIMIT
                    page["continue"] = token
                    minted = True
            except urllib.error.HTTPError as e:
                # Relist only for tokens this loop minted mid-listing
                # (matching the official client: an explicit caller token
                # that is stale is the caller's protocol error to see).
                if e.code != 410 or attempt or not minted:
                    raise
                self._log.warning("k8s_list_expired_continue", path=path)
        raise AssertionError("unreachable: second attempt returns or raises")

    def _k8s_write(self, method: str, path: str, payload: dict | None = None,
                   content_type: str = "application/strategic-merge-patch+json"
                   ) -> bool:
        req = urllib.request.Request(
            self.k8s_url + path, method=method,
            data=json.dumps(payload).encode() if payload is not None else None)
        if self._token:
            req.add_header("Authorization", f"Bearer {self._token}")
        if payload is not None:
            req.add_header("Content-Type", content_type)
        try:
            with urllib.request.urlopen(req, timeout=self.timeout_s,
                                        context=self._ctx) as resp:
                return 200 <= resp.status < 300
        except (OSError, http.client.HTTPException) as exc:
            self._log.error("k8s_write_failed", method=method, path=path,
                            error=str(exc))
            return False

    # -- K8s object mapping ----------------------------------------------

    @staticmethod
    def _service_of(meta: dict) -> str:
        labels = meta.get("labels") or {}
        return labels.get("app") or labels.get("app.kubernetes.io/name") or meta.get("name", "")

    @staticmethod
    def _owner_deployment(meta: dict) -> str:
        for ref in meta.get("ownerReferences") or []:
            if ref.get("kind") == "ReplicaSet":
                name = ref.get("name", "")
                return name.rsplit("-", 1)[0] if "-" in name else name
            if ref.get("kind") == "Deployment":
                return ref.get("name", "")
        return ""

    def list_pods(self, namespace: str, service: str | None = None) -> list[PodState]:
        params = {"labelSelector": f"app={service}"} if service else None
        out: list[PodState] = []
        for item in self._k8s_list(f"/api/v1/namespaces/{namespace}/pods", params):
            meta, spec, status = item["metadata"], item.get("spec", {}), item.get("status", {})
            waiting = terminated = None
            restarts = 0
            probe_failing = False
            statuses: list[dict] = []
            for cs in status.get("containerStatuses") or []:
                restarts += int(cs.get("restartCount", 0))
                state = cs.get("state") or {}
                # per-container review detail, the reference's payload shape
                # (kubernetes_collector.py:218-245)
                sinfo: dict = {"name": cs.get("name", ""),
                               "ready": bool(cs.get("ready", False)),
                               "restart_count": int(cs.get("restartCount", 0))}
                if "waiting" in state:
                    sinfo["waiting"] = {
                        "reason": state["waiting"].get("reason"),
                        "message": state["waiting"].get("message")}
                    if waiting is None:
                        waiting = state["waiting"].get("reason")
                if "terminated" in state:
                    sinfo["terminated"] = {
                        "reason": state["terminated"].get("reason"),
                        "exit_code": state["terminated"].get("exitCode")}
                lt = (cs.get("lastState") or {}).get("terminated")
                if lt:
                    sinfo["last_terminated"] = {
                        "reason": lt.get("reason"),
                        "exit_code": lt.get("exitCode")}
                statuses.append(sinfo)
                last = lt or state.get("terminated")
                if last and terminated is None:
                    terminated = last.get("reason")
                if "running" in state and not cs.get("ready", True):
                    probe_failing = True
            ready = False
            not_ready_s = 0.0
            for cond in status.get("conditions") or []:
                if cond.get("type") == "Ready":
                    ready = cond.get("status") == "True"
                    if not ready and cond.get("lastTransitionTime"):
                        not_ready_s = max(0.0, (utcnow() - parse_iso(
                            cond["lastTransitionTime"])).total_seconds())
            resources = {
                c["name"]: {"requests": (c.get("resources") or {}).get("requests"),
                            "limits": (c.get("resources") or {}).get("limits")}
                for c in spec.get("containers") or [] if c.get("resources")}
            out.append(PodState(
                name=meta["name"], namespace=namespace,
                deployment=self._owner_deployment(meta) or self._service_of(meta),
                service=self._service_of(meta),
                node=spec.get("nodeName", ""),
                phase=status.get("phase", "Unknown"),
                ready=ready, restart_count=restarts,
                waiting_reason=waiting, terminated_reason=terminated,
                not_ready_seconds=not_ready_s,
                readiness_probe_failing=probe_failing,
                started_at=parse_iso(status["startTime"]) if status.get("startTime") else None,
                creation_ts=parse_iso(meta["creationTimestamp"])
                if meta.get("creationTimestamp") else None,
                conditions=[{"type": c.get("type"), "status": c.get("status"),
                             "reason": c.get("reason")}
                            for c in status.get("conditions") or []],
                container_statuses=statuses,
                resources=resources,
                labels=dict(meta.get("labels") or {}),
            ))
        return sorted(out, key=lambda p: p.name)

    def list_deployments(self, namespace: str, service: str | None = None) -> list[DeploymentState]:
        params = {"labelSelector": f"app={service}"} if service else None
        out: list[DeploymentState] = []
        for item in self._k8s_list(
                f"/apis/apps/v1/namespaces/{namespace}/deployments", params):
            meta, spec, status = item["metadata"], item.get("spec", {}), item.get("status", {})
            containers = ((spec.get("template") or {}).get("spec") or {}).get("containers") or []
            changed_at: Optional[datetime] = None
            for cond in status.get("conditions") or []:
                if cond.get("type") == "Progressing" and cond.get("lastUpdateTime"):
                    changed_at = parse_iso(cond["lastUpdateTime"])
            hist = self.rollout_history(namespace, meta["name"])
            out.append(DeploymentState(
                name=meta["name"], namespace=namespace,
                service=self._service_of(meta),
                replicas=int(spec.get("replicas", 0)),
                ready_replicas=int(status.get("readyReplicas", 0) or 0),
                revision=int((meta.get("annotations") or {}).get(
                    "deployment.kubernetes.io/revision", 1)),
                image=containers[0]["image"] if containers else "",
                prev_image=hist[1]["image"] if len(hist) > 1 else None,
                changed_at=changed_at,
            ))
        return sorted(out, key=lambda d: d.name)

    def list_nodes(self) -> list[NodeState]:
        out = []
        for item in self._k8s_list("/api/v1/nodes"):
            conds = {c["type"]: c["status"]
                     for c in (item.get("status", {}).get("conditions") or [])}
            out.append(NodeState(name=item["metadata"]["name"], conditions=conds))
        return sorted(out, key=lambda n: n.name)

    def list_hpas(self, namespace: str, service: str | None = None) -> list[HPAState]:
        out = []
        for item in self._k8s_list(
                f"/apis/autoscaling/v2/namespaces/{namespace}/horizontalpodautoscalers"):
            spec, status = item.get("spec", {}), item.get("status", {})
            target = (spec.get("scaleTargetRef") or {}).get("name", "")
            if service and target != service:
                # scale targets are deployments; match either name
                labels = item["metadata"].get("labels") or {}
                if labels.get("app") != service:
                    continue
            cur = int(status.get("currentReplicas", 0) or 0)
            mx = int(spec.get("maxReplicas", 0) or 0)
            out.append(HPAState(
                name=item["metadata"]["name"], namespace=namespace,
                deployment=target,
                min_replicas=int(spec.get("minReplicas", 1) or 1),
                max_replicas=mx, current_replicas=cur,
                at_max=mx > 0 and cur >= mx,
            ))
        return sorted(out, key=lambda h: h.name)

    def list_configmaps(self, namespace: str) -> list[ConfigMapState]:
        out = []
        for item in self._k8s_list(f"/api/v1/namespaces/{namespace}/configmaps"):
            meta = item["metadata"]
            # K8s keeps no modification time; managedFields carries the last
            # apply time per manager (deploy_diff uses it as change signal)
            times = [f.get("time") for f in meta.get("managedFields") or [] if f.get("time")]
            changed = max((parse_iso(t) for t in times), default=None)
            if changed is None and meta.get("creationTimestamp"):
                changed = parse_iso(meta["creationTimestamp"])
            out.append(ConfigMapState(
                name=meta["name"], namespace=namespace, changed_at=changed))
        return sorted(out, key=lambda c: c.name)

    def list_events(self, namespace: str, since: datetime) -> list[EventState]:
        out = []
        for item in self._k8s_list(f"/api/v1/namespaces/{namespace}/events"):
            ts = item.get("lastTimestamp") or item.get("eventTime") \
                or (item.get("metadata") or {}).get("creationTimestamp")
            when = parse_iso(ts) if ts else None
            if when is None or when < since:
                continue
            involved = (item.get("involvedObject") or {}).get("name", "")
            out.append(EventState(
                namespace=namespace, involved_object=involved,
                reason=item.get("reason", ""), type=item.get("type", "Normal"),
                message=item.get("message", ""), timestamp=when,
            ))
        return out

    def rollout_history(self, namespace: str, deployment: str) -> list[dict]:
        """Top-2 revisions from owned ReplicaSets (the reference's
        kubectl-rollout-history analog, deploy_diff_collector.py:270-394)."""
        revisions = []
        for item in self._k8s_list(
                f"/apis/apps/v1/namespaces/{namespace}/replicasets"):
            meta = item["metadata"]
            owners = [r.get("name") for r in meta.get("ownerReferences") or []
                      if r.get("kind") == "Deployment"]
            if deployment not in owners:
                continue
            containers = (((item.get("spec") or {}).get("template") or {})
                          .get("spec") or {}).get("containers") or []
            revisions.append({
                "revision": int((meta.get("annotations") or {}).get(
                    "deployment.kubernetes.io/revision", 0)),
                "image": containers[0]["image"] if containers else "",
                "changed_at": parse_iso(meta["creationTimestamp"])
                if meta.get("creationTimestamp") else None,
            })
        revisions.sort(key=lambda r: r["revision"], reverse=True)
        return revisions[:2]

    # -- Loki -------------------------------------------------------------

    def query_logs(self, namespace: str, service: str, limit: int = 1000) -> list[str]:
        """Loki query_range, newest first (reference logs_collector.py:80-116)."""
        logql = f'{{namespace="{namespace}",app="{service}"}}'
        try:
            data = self._get(self.loki_url, "/loki/api/v1/query_range", {
                "query": logql, "limit": limit, "direction": "backward",
            })
        except (OSError, ValueError, http.client.HTTPException) as exc:
            self._log.warning("loki_query_failed", error=str(exc))
            return []
        lines: list[str] = []
        for stream in ((data.get("data") or {}).get("result") or []):
            for _ts, line in stream.get("values") or []:
                lines.append(line)
        return lines[:limit]

    # -- Prometheus --------------------------------------------------------

    def _render_query(self, namespace: str, service: str,
                      query_name: str) -> str | None:
        from .metrics import load_query_library
        for queries in load_query_library().values():
            if query_name in queries:
                return (queries[query_name]
                        .replace("{{namespace}}", namespace)
                        .replace("{{deployment}}", service)
                        .replace("{{pod_prefix}}", _pod_prefix(service)))
        return None

    def query_metric(self, namespace: str, service: str, query_name: str) -> float | None:
        """Render the named query from the promql library and take the max
        sample of a Prometheus instant query (metrics_collector.py:161-185;
        the fake backend answers the same names from its metric table)."""
        promql = self._render_query(namespace, service, query_name)
        if promql is None:
            return None
        try:
            data = self._get(self.prometheus_url, "/api/v1/query", {"query": promql})
        except (OSError, ValueError, http.client.HTTPException) as exc:
            self._log.warning("prometheus_query_failed", error=str(exc))
            return None
        results = ((data.get("data") or {}).get("result") or [])
        values = []
        for r in results:
            pair = r.get("value") or (r.get("values") or [None])[-1]
            if pair and len(pair) == 2:
                try:
                    values.append(float(pair[1]))
                except (TypeError, ValueError):
                    continue
        return max(values) if values else None

    def query_metric_range(self, namespace: str, service: str,
                           query_name: str, start_s: float,
                           end_s: float) -> list[tuple[float, float]]:
        """Prometheus ``query_range`` over the evidence window with the
        reference's step formula — step = max(15, range // 100)
        (metrics_collector.py:161-185). All result series are merged and
        time-sorted; non-finite samples are dropped (:224-236). The caller
        (collectors/metrics.py) downsamples and computes the stats block."""
        promql = self._render_query(namespace, service, query_name)
        if promql is None or end_s <= start_s:
            return []
        step = max(15, int(end_s - start_s) // 100)
        try:
            data = self._get(self.prometheus_url, "/api/v1/query_range", {
                "query": promql, "start": int(start_s), "end": int(end_s),
                "step": step,
            })
        except (OSError, ValueError, http.client.HTTPException) as exc:
            self._log.warning("prometheus_query_range_failed", error=str(exc))
            return []
        samples: list[tuple[float, float]] = []
        for r in ((data.get("data") or {}).get("result") or []):
            for pair in r.get("values") or []:
                if not pair or len(pair) != 2:
                    continue
                try:
                    ts, v = float(pair[0]), float(pair[1])
                except (TypeError, ValueError):
                    continue
                if v == float("inf") or v == float("-inf") or v != v:
                    continue
                samples.append((ts, v))
        samples.sort()
        return samples


    # -- mutations (RemediationExecutor write surface; reference
    # -- executor.py:86-307 via the kubernetes client) ---------------------

    def delete_pod(self, namespace: str, name: str) -> bool:
        """restart_pod = delete the pod (reference executor.py:86-134)."""
        return self._k8s_write(
            "DELETE", f"/api/v1/namespaces/{namespace}/pods/{name}")

    def restart_deployment(self, namespace: str, name: str) -> bool:
        """Patch the restartedAt annotation (reference executor.py:136-175)."""
        return self._k8s_write(
            "PATCH", f"/apis/apps/v1/namespaces/{namespace}/deployments/{name}",
            {"spec": {"template": {"metadata": {"annotations": {
                "kubectl.kubernetes.io/restartedAt": utcnow().isoformat()}}}}})

    def rollback_deployment(self, namespace: str, name: str) -> bool:
        """Copy the previous ReplicaSet's pod template back onto the
        deployment (reference executor.py:177-234, top-2 by revision)."""
        owned = []
        for item in self._k8s_list(
                f"/apis/apps/v1/namespaces/{namespace}/replicasets"):
            meta = item["metadata"]
            if any(r.get("kind") == "Deployment" and r.get("name") == name
                   for r in meta.get("ownerReferences") or []):
                owned.append((int((meta.get("annotations") or {}).get(
                    "deployment.kubernetes.io/revision", 0)), item))
        owned.sort(key=lambda t: t[0], reverse=True)
        if len(owned) < 2:
            self._log.error("rollback_no_previous_revision",
                            namespace=namespace, deployment=name)
            return False
        prev_template = (owned[1][1].get("spec") or {}).get("template")
        if not prev_template:
            return False
        return self._k8s_write(
            "PATCH", f"/apis/apps/v1/namespaces/{namespace}/deployments/{name}",
            {"spec": {"template": prev_template}})

    def scale_deployment(self, namespace: str, name: str, replicas: int) -> bool:
        """Patch the scale subresource (reference executor.py:236-281)."""
        return self._k8s_write(
            "PATCH",
            f"/apis/apps/v1/namespaces/{namespace}/deployments/{name}/scale",
            {"spec": {"replicas": int(replicas)}},
            content_type="application/merge-patch+json")

    def cordon_node(self, name: str) -> bool:
        """unschedulable=true (reference executor.py:283-307)."""
        return self._k8s_write(
            "PATCH", f"/api/v1/nodes/{name}",
            {"spec": {"unschedulable": True}})

    def uncordon_node(self, name: str) -> bool:
        """unschedulable=false (graft-saga compensation inverse)."""
        return self._k8s_write(
            "PATCH", f"/api/v1/nodes/{name}",
            {"spec": {"unschedulable": False}})


def make_backend(settings: Settings | None = None, **overrides) -> Any:
    """cluster_backend setting -> backend instance (fake needs a cluster
    passed explicitly; this factory covers the live path)."""
    settings = settings or get_settings()
    if settings.cluster_backend == "kubernetes":
        return LiveClusterBackend(settings, **overrides)
    raise ValueError(
        f"cluster_backend={settings.cluster_backend!r}: the fake backend is "
        "constructed from a FakeCluster (simulator.generate_cluster), not "
        "from this factory")
