"""Evidence collectors (reference src/services/collectors/__init__.py:1-14).

``collect_all`` replaces the reference's collect_all_evidence activity loop
(activities.py:26-94) — and actually runs collectors concurrently when given
an executor (the reference's docstring claimed parallel but looped
sequentially, SURVEY.md §3.6 item 9).
"""
from __future__ import annotations

from concurrent.futures import ThreadPoolExecutor
from typing import Any

from ..config import Settings
from ..models import CollectorResult, Incident
from .base import BaseCollector
from .deploy_diff import DeployDiffCollector
from .kubernetes import KubernetesCollector
from .logs import LogsCollector
from .metrics import MetricsCollector

ALL_COLLECTORS = (KubernetesCollector, LogsCollector, MetricsCollector, DeployDiffCollector)


def default_collectors(backend: Any, settings: Settings | None = None) -> list[BaseCollector]:
    return [cls(backend, settings) for cls in ALL_COLLECTORS]


def collect_all(
    incident: Incident,
    collectors: list[BaseCollector],
    parallel: bool = True,
) -> list[CollectorResult]:
    if parallel and len(collectors) > 1:
        with ThreadPoolExecutor(max_workers=len(collectors)) as pool:
            return list(pool.map(lambda c: c.run(incident), collectors))
    return [c.run(incident) for c in collectors]


__all__ = [
    "ALL_COLLECTORS", "BaseCollector", "KubernetesCollector", "LogsCollector",
    "MetricsCollector", "DeployDiffCollector", "collect_all", "default_collectors",
]
