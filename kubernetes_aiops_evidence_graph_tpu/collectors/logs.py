"""Log collector — pattern scanning over backend log lines.

Parity with the reference LogsCollector (logs_collector.py:20-39 pattern
catalog, :167-241 scanning and signal heuristic): the same 10 error-pattern
categories plus an explicit ``timeout`` category (the reference's
network_error rule referenced raw phrases no collector ever emitted —
SURVEY.md §3.6; here patterns_found speaks the same category vocabulary the
ruleset matches on), ≤10 sample errors, and the >10-errors/0.95-critical
signal heuristic. Emits one LOG_SIGNAL evidence per incident.
"""
from __future__ import annotations

import re

from ..models import CollectorResult, EvidenceSource, EvidenceType, Incident
from .base import BaseCollector

# category -> compiled regex (reference logs_collector.py:20-31, + timeout)
ERROR_PATTERNS: dict[str, re.Pattern] = {
    "error": re.compile(r"\b(error|err)\b", re.I),
    "critical": re.compile(r"\b(critical|fatal|panic)\b", re.I),
    "oom": re.compile(r"out of memory|oom[- ]?kill", re.I),
    "network": re.compile(r"\b(network unreachable|no route to host|dial tcp)\b", re.I),
    "auth": re.compile(r"\b(unauthorized|forbidden|permission denied|auth)\b", re.I),
    "missing": re.compile(r"\b(not found|no such file|missing)\b", re.I),
    "null_pointer": re.compile(r"(nil pointer|null pointer|NoneType)", re.I),
    "connection": re.compile(r"connection (refused|reset|closed)", re.I),
    "disk": re.compile(r"\b(no space left|disk full|i/o error)\b", re.I),
    "tls": re.compile(r"\b(tls|x509|certificate)\b", re.I),
    "timeout": re.compile(r"\btime[d]? ?out\b", re.I),
}

_NETWORK_CATEGORIES = ("network", "connection", "timeout")

STACK_TRACE_PATTERNS = (
    re.compile(r"^\s+at [\w.$]+\(.*\)"),              # Java
    re.compile(r'^\s*File ".*", line \d+'),           # Python
    re.compile(r"^goroutine \d+ \["),                 # Go
    re.compile(r"^\s+at .* \(.*:\d+:\d+\)"),          # Node
)


class LogsCollector(BaseCollector):
    name = "logs"
    source = EvidenceSource.LOKI

    def _scan(self, lines: list[str]):
        """Pattern scan: native single-pass scanner when built
        (native/kaeg_native.cpp scan_logs), else the Python regex loop.
        Both produce identical (patterns_found order, error_count,
        network_error_count, samples) — enforced by tests/test_native.py."""
        from .. import native as _native
        native_out = _native.scan_logs_native(lines) if _native.available() else None
        patterns_found: list[str] = []
        error_count = 0
        network_error_count = 0
        samples: list[str] = []
        if native_out is not None:
            _counts, flags = native_out
            cats = [c for c, _a, _b in _native.LOG_CATEGORIES]
            net_mask = sum(1 << i for i, c in enumerate(cats)
                           if c in _NETWORK_CATEGORIES)
            for i, line in enumerate(lines):
                bits = int(flags[i])
                if not bits:
                    continue
                for ci, cat in enumerate(cats):
                    if bits >> ci & 1 and cat not in patterns_found:
                        patterns_found.append(cat)
                error_count += 1
                network_error_count += (bits & net_mask).bit_count()
                if len(samples) < 10:
                    samples.append(line[:500])
            return patterns_found, error_count, network_error_count, samples
        for line in lines:
            matched_any = False
            for category, rx in ERROR_PATTERNS.items():
                if rx.search(line):
                    if category not in patterns_found:
                        patterns_found.append(category)
                    matched_any = True
                    if category in _NETWORK_CATEGORIES:
                        network_error_count += 1
            if matched_any:
                error_count += 1
                if len(samples) < 10:  # :205-219
                    samples.append(line[:500])
        return patterns_found, error_count, network_error_count, samples

    def collect(self, incident: Incident) -> CollectorResult:
        result = CollectorResult(collector_name=self.name)
        if not incident.service:
            return result
        lines = self.backend.query_logs(
            incident.namespace, incident.service, limit=self.settings.max_log_lines)
        if not lines:
            return result

        patterns_found, error_count, network_error_count, samples = (
            self._scan(lines))
        traces: list[str] = []
        for line in lines:
            for trx in STACK_TRACE_PATTERNS:
                if trx.match(line) and len(traces) < 5:
                    traces.append(line[:500])

        # signal heuristic (:221-241)
        strength = 0.5
        if error_count > 10:
            strength = 0.9
        if "oom" in patterns_found or "critical" in patterns_found:
            strength = 0.95

        result.evidence.append(self.make_evidence(
            incident, EvidenceType.LOG_SIGNAL, incident.service,
            {
                "patterns_found": patterns_found,
                "error_count": error_count,
                "network_error_count": network_error_count,
                "sample_errors": samples,
                "stack_traces": traces,
                "lines_scanned": len(lines),
            },
            signal_strength=strength, is_anomaly=error_count > 10,
        ))
        return result
