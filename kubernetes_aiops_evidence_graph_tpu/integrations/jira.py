"""Jira integration — incident tickets.

Parity with the reference JiraClient (slack_client.py:116-206): creates a
Bug issue carrying the RCA description with the severity→priority map;
REST call gated on configuration with an offline queue.
"""
from __future__ import annotations

import base64
import json
import urllib.request
from typing import Optional

from ..config import Settings, get_settings
from ..models import Hypothesis, Incident

_PRIORITY = {
    "critical": "Highest", "high": "High", "medium": "Medium",
    "low": "Low", "info": "Lowest",
}


class JiraClient:
    def __init__(self, settings: Settings | None = None) -> None:
        self.settings = settings or get_settings()
        self.outbox: list[dict] = []

    @property
    def configured(self) -> bool:
        return bool(self.settings.jira_url)

    def create_incident_ticket(
        self,
        incident: Incident,
        top_hypothesis: Optional[Hypothesis] = None,
        evidence: tuple | list = (),
    ) -> dict:
        description = [f"Incident: {incident.title}",
                       f"Severity: {incident.severity.value}",
                       f"Namespace: {incident.namespace}",
                       f"Service: {incident.service or '-'}"]
        if top_hypothesis is not None:
            description += [
                "",
                f"Top hypothesis ({top_hypothesis.confidence:.0%}): "
                f"{top_hypothesis.title}",
                top_hypothesis.description,
                "Recommended actions:",
                *[f"- {a}" for a in top_hypothesis.recommended_actions],
            ]
        from ..runbook.generator import evidence_detail_lines
        detail = evidence_detail_lines(evidence)
        if detail:
            description += ["", "Key evidence:", *[f"- {d}" for d in detail]]
        payload = {
            "fields": {
                "project": {"key": self.settings.jira_project},
                "issuetype": {"name": "Bug"},
                "summary": f"[AIOps] {incident.title}",
                "description": "\n".join(description),
                "priority": {"name": _PRIORITY.get(incident.severity.value, "Medium")},
                "labels": ["aiops", f"severity-{incident.severity.value}"],
            }
        }
        if not self.configured:
            self.outbox.append(payload)
            return {"created": False, "queued": True, "payload": payload}
        req = urllib.request.Request(
            f"{self.settings.jira_url}/rest/api/2/issue",
            data=json.dumps(payload).encode(),
            headers={
                "Content-Type": "application/json",
                "Authorization": "Basic " + base64.b64encode(
                    f"{self.settings.jira_user}:{self.settings.jira_token}".encode()
                ).decode(),
            })
        with urllib.request.urlopen(req, timeout=15) as resp:  # noqa: S310
            body = json.loads(resp.read())
        return {"created": True, "key": body.get("key")}
