"""Slack integration — approval requests with a REAL response path.

The reference posts a Block Kit message and then always returns
not-approved/pending because no interactive callback exists
(slack_client.py:47-54, SURVEY.md §3.6 item 8). Here approvals are
first-class: requests are registered in an ApprovalBroker that the HTTP
API's /approvals endpoints resolve (or tests resolve directly), and the
Slack webhook post is just a notification transport — gated on
configuration, with an offline queue when no URL is set.
"""
from __future__ import annotations

import json
import threading
import urllib.request
from dataclasses import dataclass, field
from typing import Optional

from ..config import Settings, get_settings
from ..models import ApprovalRequest, ApprovalResponse
from ..utils.timeutils import utcnow


@dataclass
class _Pending:
    request: ApprovalRequest
    event: threading.Event = field(default_factory=threading.Event)
    response: Optional[ApprovalResponse] = None


class ApprovalBroker:
    """In-process approval registry: request → (wait | resolve)."""

    def __init__(self) -> None:
        self._pending: dict[str, _Pending] = {}
        self._lock = threading.Lock()

    def register(self, request: ApprovalRequest) -> str:
        key = str(request.action_id)
        with self._lock:
            self._pending[key] = _Pending(request=request)
        return key

    def resolve(self, action_id: str, approved: bool, responder: str = "operator",
                notes: str | None = None) -> bool:
        with self._lock:
            p = self._pending.get(str(action_id))
            if p is None:
                return False
            p.response = ApprovalResponse(
                action_id=p.request.action_id, approved=approved,
                responder=responder, responded_at=utcnow(), notes=notes)
            p.event.set()
            return True

    def wait(self, action_id: str, timeout_s: float) -> Optional[ApprovalResponse]:
        with self._lock:
            p = self._pending.get(str(action_id))
        if p is None:
            return None
        p.event.wait(timeout_s)
        with self._lock:
            self._pending.pop(str(action_id), None)
        return p.response

    def pending(self) -> list[ApprovalRequest]:
        with self._lock:
            return [p.request for p in self._pending.values()
                    if p.response is None]


BROKER = ApprovalBroker()


class SlackClient:
    def __init__(self, settings: Settings | None = None,
                 broker: ApprovalBroker | None = None) -> None:
        self.settings = settings or get_settings()
        self.broker = broker or BROKER
        self.outbox: list[dict] = []  # offline queue when unconfigured

    @property
    def configured(self) -> bool:
        return bool(self.settings.slack_webhook_url)

    def _post(self, payload: dict) -> bool:
        if not self.configured:
            self.outbox.append(payload)
            return False
        req = urllib.request.Request(
            self.settings.slack_webhook_url,
            data=json.dumps(payload).encode(),
            headers={"Content-Type": "application/json"})
        with urllib.request.urlopen(req, timeout=10) as resp:  # noqa: S310
            return resp.status == 200

    def notify(self, text: str) -> bool:
        return self._post({"channel": self.settings.slack_channel, "text": text})

    def request_approval(self, request: ApprovalRequest,
                         timeout_s: float | None = None) -> ApprovalResponse | None:
        """Register with the broker, notify Slack, block for resolution."""
        self.broker.register(request)
        self._post({
            "channel": self.settings.slack_channel,
            "text": (f"Approval needed: {request.action_type.value} on "
                     f"{request.target_resource} ({request.target_namespace}) — "
                     f"risk {request.risk_level.value}, "
                     f"blast {request.blast_radius_score:.0f}. "
                     f"Resolve via POST /api/v1/approvals/{request.action_id}"),
            "blocks": [{
                "type": "section",
                "text": {"type": "mrkdwn",
                         "text": f"*{request.incident_title}*\n{request.hypothesis_summary}"},
            }],
        })
        timeout = timeout_s if timeout_s is not None else (
            self.settings.approval_timeout_seconds)
        return self.broker.wait(str(request.action_id), timeout)
