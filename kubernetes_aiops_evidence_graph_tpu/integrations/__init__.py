from .jira import JiraClient
from .slack import BROKER, ApprovalBroker, SlackClient

__all__ = ["ApprovalBroker", "BROKER", "SlackClient", "JiraClient"]
