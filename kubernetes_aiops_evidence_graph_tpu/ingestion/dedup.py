"""Fingerprint dedup + rate limiting (no Redis: in-process TTL store).

Parity with the reference AlertDeduplicator/RateLimiter
(deduplicator.py:16-177) with its two defects fixed (SURVEY.md §3.6):

* fingerprints are ACTUALLY REGISTERED on incident creation (the reference
  defined register_fingerprint but never called it — defect 4), with the
  same 4h TTL (deduplicator.py:20);
* duplicate checks fail open like the reference (:69-72), and the Postgres
  UNIQUE-constraint backstop survives as the storage layer's open-
  fingerprint index.

The rate limiter keeps the reference's fixed-window INCR+EXPIRE semantics
(:147-177) at 100 req/min (settings.py:119).
"""
from __future__ import annotations

import threading
import time

import numpy as np

from ..config import Settings, get_settings
from ..observability import metrics as obs_metrics


class TTLSet:
    """Monotonic-clock TTL set with lazy expiry."""

    def __init__(self, clock=time.monotonic) -> None:
        self._clock = clock
        self._expiry: dict[str, float] = {}
        self._lock = threading.Lock()

    def add(self, key: str, ttl_s: float) -> None:
        with self._lock:
            self._expiry[key] = self._clock() + ttl_s

    def __contains__(self, key: str) -> bool:
        with self._lock:
            exp = self._expiry.get(key)
            if exp is None:
                return False
            if exp < self._clock():
                del self._expiry[key]
                return False
            return True

    def discard(self, key: str) -> None:
        with self._lock:
            self._expiry.pop(key, None)

    def purge(self) -> int:
        now = self._clock()
        with self._lock:
            dead = [k for k, exp in self._expiry.items() if exp < now]
            for k in dead:
                del self._expiry[k]
            return len(dead)


class FingerprintRing:
    """graft-intake: bounded hashed fingerprint window for dedup.

    Open-addressed ``(hash, expiry)`` slot arrays (capacity rounded up to
    a power of two) replacing the unbounded dict TTL store on the
    columnar path: every op is O(probes), batch membership checks are
    VECTORIZED (one array compare per probe round over the whole batch —
    the storm-shaped operation), and memory is fixed. A full probe
    neighborhood evicts its oldest-expiry entry, counted in
    ``aiops_ingest_dedup_evictions_total``; live-slot occupancy feeds the
    ``aiops_ingest_dedup_window_occupancy`` gauge. Fingerprints are
    identified by their leading 64 hash bits — a collision reads as a
    duplicate (an alert suppressed for one TTL), the same fail-closed
    trade the reference's fingerprint truncation already makes.
    """

    _TOMBSTONE = np.uint64(0)     # empty-or-released slot

    def __init__(self, capacity: int = 32768, probes: int = 8,
                 clock=time.monotonic) -> None:
        cap = 1
        while cap < max(int(capacity), probes * 2):
            cap *= 2
        self._mask = np.uint64(cap - 1)
        self.capacity = cap
        self.probes = int(probes)
        self._clock = clock
        self._hash = np.zeros(cap, np.uint64)
        self._expiry = np.zeros(cap, np.float64)
        self.evictions = 0
        self._lock = threading.Lock()

    @staticmethod
    def _h(fingerprint: str) -> np.uint64:
        # leading 64 bits of the (already sha256-derived) hex fingerprint;
        # 0 is reserved as the empty marker, so only the single value 0
        # remaps (an |1-style trick would collapse even/odd hash pairs)
        v = int(str(fingerprint)[:16], 16)
        return np.uint64(v if v else 0x9E3779B97F4A7C15)

    def _hash_batch(self, fingerprints) -> np.ndarray:
        """Per-unique hashing: a storm batch repeats few fingerprints."""
        fps = np.asarray(fingerprints, dtype=object)
        uniq, inv = np.unique(fps, return_inverse=True)
        hu = np.fromiter((self._h(u) for u in uniq), np.uint64,
                         count=len(uniq))
        return hu[inv]

    # -- single-key API (AlertDeduplicator back-compat surface) -----------

    def __contains__(self, fingerprint: str) -> bool:
        return bool(self.contains_batch([fingerprint])[0])

    def add(self, fingerprint: str, ttl_s: float) -> None:
        now = self._clock()
        with self._lock:
            self._add_one(self._h(fingerprint), now + ttl_s, now)
            obs_metrics.INGEST_DEDUP_OCCUPANCY.set(
                float(((self._hash != self._TOMBSTONE)
                       & (self._expiry >= now)).sum()))

    def discard(self, fingerprint: str) -> None:
        h = self._h(fingerprint)
        base, mask = int(h), int(self._mask)
        with self._lock:
            for p in range(self.probes):
                slot = (base + p) & mask
                if self._hash[slot] == h:
                    self._hash[slot] = self._TOMBSTONE
                    self._expiry[slot] = 0.0
                    return

    # -- batch API (the columnar ingest edge) ------------------------------

    def contains_batch(self, fingerprints) -> np.ndarray:
        """[B] bool duplicate mask: one vectorized slot compare per probe
        round over the whole batch."""
        if len(fingerprints) == 0:
            return np.zeros(0, bool)
        h = self._hash_batch(fingerprints)
        now = self._clock()
        hit = np.zeros(len(h), bool)
        with self._lock:
            for p in range(self.probes):
                slots = ((h + np.uint64(p)) & self._mask).astype(np.int64)
                hit |= (self._hash[slots] == h) & (self._expiry[slots] >= now)
        return hit

    def add_batch(self, fingerprints, ttl_s: float) -> None:
        if len(fingerprints) == 0:
            return
        h = self._hash_batch(fingerprints)
        now = self._clock()
        exp = now + ttl_s
        with self._lock:
            for hv in h:
                self._add_one(hv, exp, now)
            obs_metrics.INGEST_DEDUP_OCCUPANCY.set(
                float(((self._hash != self._TOMBSTONE)
                       & (self._expiry >= now)).sum()))

    def _add_one(self, h: np.uint64, exp: float, now: float) -> None:
        """Place one hash: refresh an existing live slot, else the first
        free/expired slot in the probe neighborhood, else evict the
        neighborhood's oldest-expiry entry (counted). Caller holds the
        lock."""
        free = -1
        oldest_slot, oldest_exp = -1, np.inf
        base, mask = int(h), int(self._mask)
        for p in range(self.probes):
            slot = (base + p) & mask
            if self._hash[slot] == h:
                self._expiry[slot] = exp
                return
            e = self._expiry[slot]
            if free < 0 and (self._hash[slot] == self._TOMBSTONE
                             or e < now):
                free = slot
            if e < oldest_exp:
                oldest_slot, oldest_exp = slot, e
        if free < 0:
            free = oldest_slot
            self.evictions += 1
            obs_metrics.INGEST_DEDUP_EVICTIONS.inc()
        self._hash[free] = h
        self._expiry[free] = exp

    def occupancy(self) -> int:
        now = self._clock()
        with self._lock:
            return int(((self._hash != self._TOMBSTONE)
                        & (self._expiry >= now)).sum())


class AlertDeduplicator:
    """Dedup facade over the TTL window. With ``settings.ingest_columnar``
    the window is the hashed :class:`FingerprintRing` (bounded, batch
    probes for the columnar ingest edge); without it, the original dict
    :class:`TTLSet` — the behavioral oracle the contract tests compare
    against."""

    def __init__(self, settings: Settings | None = None, clock=time.monotonic) -> None:
        self.settings = settings or get_settings()
        self._seen: "TTLSet | FingerprintRing"
        if getattr(self.settings, "ingest_columnar", False):
            self._seen = FingerprintRing(
                capacity=getattr(self.settings, "ingest_dedup_window", 32768),
                clock=clock)
        else:
            self._seen = TTLSet(clock)

    def check_duplicate(self, fingerprint: str) -> bool:
        try:
            return fingerprint in self._seen
        except Exception:  # graft-audit: allow[broad-except] fail open (deduplicator.py:69-72): dedup errors must not drop alerts
            return False

    def register_fingerprint(self, fingerprint: str) -> None:
        self._seen.add(fingerprint, self.settings.dedup_ttl_seconds)

    def release(self, fingerprint: str) -> None:
        """Allow re-alerting once an incident resolves."""
        self._seen.discard(fingerprint)

    # -- batch surface (columnar ingest edge; graft-intake) ---------------

    def check_batch(self, fingerprints) -> np.ndarray:
        """[B] bool duplicate mask. Vectorized on the ring; the TTLSet
        oracle answers per key (fail-open per row, like check_duplicate)."""
        ring = self._seen
        if isinstance(ring, FingerprintRing):
            try:
                return ring.contains_batch(fingerprints)
            except Exception:  # graft-audit: allow[broad-except] fail open: dedup errors must not drop alerts
                return np.zeros(len(fingerprints), bool)
        return np.array([self.check_duplicate(f) for f in fingerprints],
                        bool)

    def register_batch(self, fingerprints) -> None:
        ttl = self.settings.dedup_ttl_seconds
        ring = self._seen
        if isinstance(ring, FingerprintRing):
            ring.add_batch(fingerprints, ttl)
            return
        for f in fingerprints:
            ring.add(f, ttl)


class RateLimiter:
    """Fixed one-minute windows per client key (deduplicator.py:147-177).

    graft-storm fixed its unbounded-memory defect: ``_windows`` grew one
    entry per distinct client key FOREVER (a storm from many source IPs
    = a memory leak). Entries from previous windows are now pruned when
    the limiter first observes a new window — the sweep runs at most
    once per window roll, so the steady-state cost is unchanged. The
    columnar webhook path replaces this limiter entirely with the
    severity-aware per-tenant token-bucket gate
    (ingestion/admission.AdmissionController); this stays as the
    dict-path oracle's request gate, now with a ``retry_after_s`` so
    429 responses can carry Retry-After.
    """

    def __init__(self, settings: Settings | None = None, clock=time.monotonic) -> None:
        self.settings = settings or get_settings()
        self._clock = clock
        self._windows: dict[str, tuple[int, int]] = {}  # key -> (window, count)
        self._cur_window = -1
        self._lock = threading.Lock()

    def check_rate_limit(self, client: str) -> bool:
        """True when the request is allowed."""
        window = int(self._clock() // 60)
        limit = self.settings.webhook_rate_limit_per_minute
        with self._lock:
            if window != self._cur_window:
                # window rolled: every entry stamped with an older window
                # is dead weight — prune them all in one sweep
                self._windows = {k: v for k, v in self._windows.items()
                                 if v[0] == window}
                self._cur_window = window
            w, count = self._windows.get(client, (window, 0))
            if w != window:
                w, count = window, 0
            count += 1
            self._windows[client] = (w, count)
            return count <= limit

    def retry_after_s(self) -> float:
        """Seconds until the current fixed window rolls — the
        Retry-After a 429 from this limiter carries."""
        now = self._clock()
        return max(60.0 - (now % 60.0), 0.0)

    def tracked_clients(self) -> int:
        with self._lock:
            return len(self._windows)
