"""Fingerprint dedup + rate limiting (no Redis: in-process TTL store).

Parity with the reference AlertDeduplicator/RateLimiter
(deduplicator.py:16-177) with its two defects fixed (SURVEY.md §3.6):

* fingerprints are ACTUALLY REGISTERED on incident creation (the reference
  defined register_fingerprint but never called it — defect 4), with the
  same 4h TTL (deduplicator.py:20);
* duplicate checks fail open like the reference (:69-72), and the Postgres
  UNIQUE-constraint backstop survives as the storage layer's open-
  fingerprint index.

The rate limiter keeps the reference's fixed-window INCR+EXPIRE semantics
(:147-177) at 100 req/min (settings.py:119).
"""
from __future__ import annotations

import threading
import time

from ..config import Settings, get_settings


class TTLSet:
    """Monotonic-clock TTL set with lazy expiry."""

    def __init__(self, clock=time.monotonic) -> None:
        self._clock = clock
        self._expiry: dict[str, float] = {}
        self._lock = threading.Lock()

    def add(self, key: str, ttl_s: float) -> None:
        with self._lock:
            self._expiry[key] = self._clock() + ttl_s

    def __contains__(self, key: str) -> bool:
        with self._lock:
            exp = self._expiry.get(key)
            if exp is None:
                return False
            if exp < self._clock():
                del self._expiry[key]
                return False
            return True

    def discard(self, key: str) -> None:
        with self._lock:
            self._expiry.pop(key, None)

    def purge(self) -> int:
        now = self._clock()
        with self._lock:
            dead = [k for k, exp in self._expiry.items() if exp < now]
            for k in dead:
                del self._expiry[k]
            return len(dead)


class AlertDeduplicator:
    def __init__(self, settings: Settings | None = None, clock=time.monotonic) -> None:
        self.settings = settings or get_settings()
        self._seen = TTLSet(clock)

    def check_duplicate(self, fingerprint: str) -> bool:
        try:
            return fingerprint in self._seen
        except Exception:  # graft-audit: allow[broad-except] fail open (deduplicator.py:69-72): dedup errors must not drop alerts
            return False

    def register_fingerprint(self, fingerprint: str) -> None:
        self._seen.add(fingerprint, self.settings.dedup_ttl_seconds)

    def release(self, fingerprint: str) -> None:
        """Allow re-alerting once an incident resolves."""
        self._seen.discard(fingerprint)


class RateLimiter:
    """Fixed one-minute windows per client key (deduplicator.py:147-177)."""

    def __init__(self, settings: Settings | None = None, clock=time.monotonic) -> None:
        self.settings = settings or get_settings()
        self._clock = clock
        self._windows: dict[str, tuple[int, int]] = {}  # key -> (window, count)
        self._lock = threading.Lock()

    def check_rate_limit(self, client: str) -> bool:
        """True when the request is allowed."""
        window = int(self._clock() // 60)
        limit = self.settings.webhook_rate_limit_per_minute
        with self._lock:
            w, count = self._windows.get(client, (window, 0))
            if w != window:
                w, count = window, 0
            count += 1
            self._windows[client] = (w, count)
            return count <= limit
