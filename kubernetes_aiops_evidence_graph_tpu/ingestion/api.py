"""HTTP API — webhooks, incidents, graph, approvals, health, metrics.

Route parity with the reference FastAPI app (ingestion/main.py:65-425):
POST /api/v1/webhooks/{alertmanager,grafana}, incident CRUD + listing with
filters, the incident graph endpoint (depth-limited subgraph), /health,
/health/ready and /metrics — plus the approvals endpoints the reference
lacked (its Slack approval flow had no response path, SURVEY.md §3.6
item 8). Built on the stdlib ThreadingHTTPServer: no FastAPI/uvicorn in
this image, and the ingestion edge is not the hot path — the TPU scorer is.

Also fixes reference defect 1: the served entrypoint actually exists
(`python -m kubernetes_aiops_evidence_graph_tpu.serve`).
"""
from __future__ import annotations

import json
import re
import threading
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Any, Callable
from urllib.parse import parse_qs, urlparse

from ..models import IncidentStatus
from ..observability import (
    ALERTS_DEDUPLICATED,
    ALERTS_RECEIVED,
    INCIDENTS_CREATED,
    REGISTRY,
    TRACER,
    WEBHOOK_LATENCY,
    get_logger,
)
from ..observability.scope import FLIGHT_RECORDER, SCOPE
from ..storage import DuplicateIncidentError

log = get_logger("api")

_ROUTES: list[tuple[str, re.Pattern, str]] = []  # (method, pattern, handler name)


def route(method: str, pattern: str):
    def deco(fn):
        _ROUTES.append((method, re.compile(f"^{pattern}$"), fn.__name__))
        return fn
    return deco


class ApiHandler(BaseHTTPRequestHandler):
    app: "Any" = None  # set by make_server

    # -- plumbing ---------------------------------------------------------

    def log_message(self, fmt, *args):  # silence default stderr spam
        pass

    def _json(self, status: int, payload: Any,
              headers: "dict[str, str] | None" = None) -> None:
        body = json.dumps(payload, default=str).encode()
        self.send_response(status)
        self.send_header("Content-Type", "application/json")
        self.send_header("Content-Length", str(len(body)))
        for k, v in (headers or {}).items():
            self.send_header(k, v)
        self.end_headers()
        self.wfile.write(body)

    def _text(self, status: int, text: str, content_type="text/plain") -> None:
        body = text.encode()
        self.send_response(status)
        self.send_header("Content-Type", content_type)
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)

    def _body(self) -> dict:
        length = int(self.headers.get("Content-Length") or 0)
        if not length:
            return {}
        try:
            return json.loads(self.rfile.read(length))
        except json.JSONDecodeError:
            return {}

    def _dispatch(self, method: str) -> None:
        parsed = urlparse(self.path)
        self.query = {k: v[0] for k, v in parse_qs(parsed.query).items()}
        for m, pattern, name in _ROUTES:
            if m != method:
                continue
            match = pattern.match(parsed.path)
            if match:
                try:
                    getattr(self, name)(**match.groupdict())
                except Exception as exc:  # graft-audit: allow[broad-except] HTTP boundary: handler errors become a 500, server stays up
                    log.error("handler_error", path=parsed.path, error=str(exc))
                    self._json(500, {"error": str(exc)})
                return
        self._json(404, {"error": f"no route {method} {parsed.path}"})

    def do_GET(self):
        self._dispatch("GET")

    def do_POST(self):
        self._dispatch("POST")

    def do_PATCH(self):
        self._dispatch("PATCH")

    # -- health & metrics (main.py:83-112) --------------------------------

    @route("GET", "/health")
    def health(self):
        self._json(200, {"status": "healthy", "service": self.app.settings.app_name})

    @route("GET", "/health/ready")
    def ready(self):
        ok = self.app.ready()
        self._json(200 if ok else 503, {"ready": ok})

    @route("GET", "/metrics")
    def metrics(self):
        self._text(200, REGISTRY.expose(), "text/plain; version=0.0.4")

    # -- webhooks (main.py:116-254) ---------------------------------------
    #
    # graft-intake: with settings.ingest_columnar the batch rides the
    # vectorized columnar pipeline (ingestion/columnar.py — one payload
    # transpose, array-op normalize, batch dedup probe, pydantic only for
    # survivors; malformed rows masked + counted, never a 500). The
    # per-row dict path below each handler is the behavioral oracle.

    def _columnar_webhook(self, source: str, normalize, t_parse: float):
        """Shared columnar handler tail: normalize → batch ingest →
        per-stage aiops_ingest_* accounting. ``t_parse`` is the JSON
        parse wall already spent in ``_body``. Returns the
        :class:`~..app.IngestBatchResult` — the caller renders the
        response (200 with shed accounting, or a full-shed 429 with
        Retry-After)."""
        from ..observability.metrics import (
            INGEST_BATCH_FILL, INGEST_MALFORMED_ROWS, INGEST_ROWS,
            INGEST_ROWS_PER_SEC, INGEST_STAGE_SECONDS)
        t1 = time.perf_counter()
        cols = normalize()
        t2 = time.perf_counter()
        res = self.app.ingest_batch(cols)
        t3 = time.perf_counter()
        n = len(cols)
        ALERTS_RECEIVED.inc(float(n), source=source)
        for iid, ns in res.created:
            SCOPE.webhook_received(iid, tenant=ns or "default")
        INGEST_STAGE_SECONDS.observe(t_parse, stage="parse", source=source)
        INGEST_STAGE_SECONDS.observe(t2 - t1, stage="normalize",
                                     source=source)
        # dedup probe + admission gate + spec construction + DB insert
        # ride ingest_batch; the probe/gate are a handful of vectorized
        # compares, so the window is reported as one "persist" stage with
        # dedup hits / sheds counted separately
        INGEST_STAGE_SECONDS.observe(t3 - t2, stage="persist",
                                     source=source)
        if n:
            eligible = int(cols.eligible.sum())
            INGEST_ROWS.inc(float(len(res.created)), source=source,
                            outcome="created")
            INGEST_ROWS.inc(float(res.duplicates), source=source,
                            outcome="duplicate")
            INGEST_ROWS.inc(float(n - cols.malformed - eligible),
                            source=source, outcome="not_firing")
            for outcome, count in (("shed", res.shed),
                                   ("storm_sampled", res.sampled),
                                   ("spilled", res.spilled)):
                if count:
                    INGEST_ROWS.inc(float(count), source=source,
                                    outcome=outcome)
            if cols.malformed:
                INGEST_ROWS.inc(float(cols.malformed), source=source,
                                outcome="malformed")
                INGEST_MALFORMED_ROWS.inc(float(cols.malformed),
                                          source=source)
            INGEST_BATCH_FILL.set(eligible / n, site="webhook")
            wall = t_parse + (t3 - t1)
            if wall > 0:
                INGEST_ROWS_PER_SEC.set(n / wall, source=source)
        return res

    def _rate_limited(self) -> None:
        """Legacy fixed-window 429 — now with Retry-After (time to the
        next window), the header the reference limiter never sent."""
        retry = self.app.rate_limiter.retry_after_s()
        self._json(429, {"error": "rate limit exceeded",
                         "retry_after_s": round(retry, 1)},
                   headers={"Retry-After": str(max(int(retry + 0.5), 1))})

    def _columnar_response(self, res, endpoint: str, t0: float) -> None:
        """Render one columnar ingest result. A batch whose every
        admission-eligible row was shed answers 429 + Retry-After
        (token-bucket refill time); partial sheds answer 200 with exact
        accounting plus the advisory Retry-After header."""
        WEBHOOK_LATENCY.observe(time.perf_counter() - t0,
                                endpoint=endpoint)
        headers = {}
        if res.retry_after_s > 0:
            headers["Retry-After"] = str(max(int(res.retry_after_s + 0.5),
                                             1))
        body = {"created": [iid for iid, _ns in res.created],
                "duplicates": res.duplicates}
        for k in ("shed", "sampled", "spilled"):
            if getattr(res, k):
                body[k] = getattr(res, k)
        if res.shed and not res.created and not res.duplicates \
                and not res.sampled:
            self._json(429, {"error": "admission shed", **body},
                       headers=headers)
            return
        self._json(200, body, headers=headers)

    @route("POST", "/api/v1/webhooks/alertmanager")
    def webhook_alertmanager(self):
        from .normalizer import AlertNormalizer
        t0 = time.perf_counter()
        client = self.client_address[0] if self.client_address else "unknown"
        # graft-storm: the columnar path is gated by the severity-aware
        # per-tenant admission controller inside ingest_batch — the
        # per-client fixed window only guards the dict-path oracle
        if getattr(self.app, "admission", None) is None and \
                not self.app.rate_limiter.check_rate_limit(client):
            self._rate_limited()
            return
        payload = self._body()
        t_parse = time.perf_counter() - t0
        alerts = payload.get("alerts", []) or []
        if not isinstance(alerts, list):
            self._json(400, {"error": "alerts must be a list of alert objects"})
            return
        # graft-scope: the webhook span is the ROOT of the incident's
        # trace — ServeScope carries its context to the async workflow
        # (workflow/engine.py parents every step span under it) and
        # stamps the arrival time the webhook→verdict SLO measures from
        if getattr(self.app.settings, "ingest_columnar", False):
            from .columnar import normalize_alertmanager_batch
            with TRACER.span("webhook.alertmanager", alerts=len(alerts)):
                res = self._columnar_webhook(
                    "alertmanager",
                    lambda: normalize_alertmanager_batch(alerts), t_parse)
            self._columnar_response(res, "alertmanager", t0)
            return
        if any(not isinstance(a, dict) for a in alerts):
            self._json(400, {"error": "alerts must be a list of alert objects"})
            return
        created, duplicates = [], 0
        with TRACER.span("webhook.alertmanager", alerts=len(alerts)):
            for alert in alerts:
                ALERTS_RECEIVED.inc(source="alertmanager")
                if alert.get("status") != "firing":   # main.py:146-147
                    continue
                spec = AlertNormalizer.normalize_alertmanager(alert)
                incident_id = self.app.ingest(spec)
                if incident_id is None:
                    duplicates += 1
                else:
                    created.append(incident_id)
                    SCOPE.webhook_received(
                        incident_id, tenant=spec.namespace or "default")
        WEBHOOK_LATENCY.observe(time.perf_counter() - t0, endpoint="alertmanager")
        self._json(200, {"created": created, "duplicates": duplicates})

    @route("POST", "/api/v1/webhooks/grafana")
    def webhook_grafana(self):
        from .normalizer import AlertNormalizer
        t0 = time.perf_counter()
        client = self.client_address[0] if self.client_address else "unknown"
        if getattr(self.app, "admission", None) is None and \
                not self.app.rate_limiter.check_rate_limit(client):
            self._rate_limited()
            return
        payload = self._body()
        t_parse = time.perf_counter() - t0
        if getattr(self.app.settings, "ingest_columnar", False):
            from .columnar import normalize_grafana_batch
            with TRACER.span("webhook.grafana"):
                res = self._columnar_webhook(
                    "grafana",
                    lambda: normalize_grafana_batch(payload), t_parse)
            self._columnar_response(res, "grafana", t0)
            return
        created, duplicates = [], 0
        with TRACER.span("webhook.grafana"):
            for spec in AlertNormalizer.normalize_grafana(payload):
                ALERTS_RECEIVED.inc(source="grafana")
                incident_id = self.app.ingest(spec)
                if incident_id is None:
                    duplicates += 1
                else:
                    created.append(incident_id)
                    SCOPE.webhook_received(
                        incident_id, tenant=spec.namespace or "default")
        WEBHOOK_LATENCY.observe(time.perf_counter() - t0, endpoint="grafana")
        self._json(200, {"created": created, "duplicates": duplicates})

    # -- incidents (main.py:256-342) --------------------------------------

    @route("GET", "/api/v1/incidents")
    def list_incidents(self):
        try:
            limit = int(self.query.get("limit", 100))
            offset = int(self.query.get("offset", 0))
        except ValueError:
            self._json(400, {"error": "limit/offset must be integers"})
            return
        rows = self.app.db.list_incidents(
            status=self.query.get("status"),
            namespace=self.query.get("namespace"),
            severity=self.query.get("severity"),
            limit=limit,
            offset=offset,
        )
        self._json(200, {"incidents": rows, "count": len(rows)})

    @route("GET", r"/api/v1/incidents/(?P<incident_id>[0-9a-f-]+)")
    def get_incident(self, incident_id: str):
        row = self.app.db.get_incident(incident_id)
        if row is None:
            self._json(404, {"error": "incident not found"})
        else:
            self._json(200, row)

    @route("PATCH", r"/api/v1/incidents/(?P<incident_id>[0-9a-f-]+)")
    def patch_incident(self, incident_id: str):
        body = self._body()
        status = body.get("status")
        if status not in {s.value for s in IncidentStatus}:
            self._json(400, {"error": f"invalid status {status!r}"})
            return
        from ..utils.timeutils import utcnow
        resolved_at = (utcnow() if status in ("resolved", "closed") else None)
        self.app.db.update_incident_status(
            incident_id, IncidentStatus(status), resolved_at=resolved_at)
        self._json(200, self.app.db.get_incident(incident_id))

    @route("GET", r"/api/v1/incidents/(?P<incident_id>[0-9a-f-]+)/graph")
    def incident_graph(self, incident_id: str):
        depth = int(self.query.get("depth", 3))  # main.py:303 default depth=3
        self._json(200, self.app.store.get_incident_subgraph(incident_id, depth=depth))

    @route("GET", r"/api/v1/incidents/(?P<incident_id>[0-9a-f-]+)/blast-propagation")
    def incident_blast_propagation(self, incident_id: str):
        """Device-computed blast map: k-hop reach bound + label-propagation
        ranking over the tensorized graph (rca/blast.py)."""
        from ..rca.blast import blast_propagation
        out = blast_propagation(
            self.app.store, incident_id,
            settings=self.app.settings,
            hops=int(self.query.get("hops", 3)),
            iterations=int(self.query.get("iterations", 3)),
            top_k=int(self.query.get("top_k", 25)),
        )
        if out is None:
            self._json(404, {"error": "incident not in graph",
                             "incident_id": incident_id})
            return
        self._json(200, out)

    @route("GET", r"/api/v1/incidents/(?P<incident_id>[0-9a-f-]+)/evidence")
    def incident_evidence(self, incident_id: str):
        self._json(200, {"evidence": self.app.db.evidence_for(incident_id)})

    @route("GET", r"/api/v1/incidents/(?P<incident_id>[0-9a-f-]+)/hypotheses")
    def incident_hypotheses(self, incident_id: str):
        self._json(200, {"hypotheses": self.app.db.hypotheses_for(incident_id)})

    @route("GET", r"/api/v1/incidents/(?P<incident_id>[0-9a-f-]+)/runbook")
    def incident_runbook(self, incident_id: str):
        rb = self.app.db.runbook_for(incident_id)
        if rb is None:
            self._json(404, {"error": "no runbook"})
        else:
            self._json(200, rb)

    @route("GET", r"/api/v1/incidents/(?P<incident_id>[0-9a-f-]+)/actions")
    def incident_actions(self, incident_id: str):
        self._json(200, {"actions": self.app.db.actions_for(incident_id)})

    @route("GET", r"/api/v1/incidents/(?P<incident_id>[0-9a-f-]+)/status")
    def incident_workflow_status(self, incident_id: str):
        self._json(200, self.app.workflow_status(incident_id))

    # -- approvals (new; closes the reference's approval gap) -------------

    @route("GET", "/api/v1/approvals")
    def list_approvals(self):
        from ..integrations import BROKER
        self._json(200, {"pending": [r.model_dump(mode="json")
                                     for r in BROKER.pending()]})

    @route("POST", r"/api/v1/approvals/(?P<action_id>[0-9a-f-]+)")
    def resolve_approval(self, action_id: str):
        from ..integrations import BROKER
        body = self._body()
        ok = BROKER.resolve(
            action_id,
            approved=bool(body.get("approved")),
            responder=body.get("responder", "api"),
            notes=body.get("notes"),
        )
        self._json(200 if ok else 404,
                   {"resolved": ok, "action_id": action_id})

    # -- hypothesis feedback (the reference defines HypothesisFeedback but
    #    never persists or accepts it — hypothesis.py:169-176) -------------

    @route("POST", r"/api/v1/hypotheses/(?P<hypothesis_id>[0-9a-f-]+)/feedback")
    def submit_feedback(self, hypothesis_id: str):
        from pydantic import ValidationError

        from ..models import HypothesisFeedback
        body = self._body()
        try:
            fb = HypothesisFeedback(hypothesis_id=hypothesis_id, **body)
        except (ValidationError, TypeError) as exc:
            # bad request body: pydantic validation or non-str kwargs
            self._json(400, {"error": str(exc)})
            return
        if not self.app.db.insert_feedback(fb):
            self._json(404, {"error": "unknown hypothesis",
                             "hypothesis_id": str(fb.hypothesis_id)})
            return
        self._json(201, {"recorded": True,
                         "hypothesis_id": str(fb.hypothesis_id)})

    @route("GET", r"/api/v1/hypotheses/(?P<hypothesis_id>[0-9a-f-]+)/feedback")
    def list_feedback(self, hypothesis_id: str):
        self._json(200, {"feedback": self.app.db.feedback_for(hypothesis_id)})

    # -- online learning (graft-evolve, learn/) ----------------------------
    # The operator surface of the loop: POST /api/v1/feedback feeds it
    # (the flat-body twin of the per-hypothesis route above — the
    # hypothesis id rides in the body, which is what operator tooling
    # posting from an alert annotation wants), GET /api/v1/learning
    # observes it (buffer occupancy, last gate eval, swap generation).

    @route("POST", "/api/v1/feedback")
    def submit_feedback_body(self):
        from pydantic import ValidationError

        from ..models import HypothesisFeedback
        body = self._body()
        try:
            fb = HypothesisFeedback(**body)
        except (ValidationError, TypeError) as exc:
            self._json(400, {"error": str(exc)})
            return
        # orphan rejection rides the storage layer's atomic
        # existence-check-and-insert (insert_feedback's False path):
        # feedback for a hypothesis re-analysis deleted must 404, not
        # silently poison the learning loop's label harvest
        if not self.app.db.insert_feedback(fb):
            self._json(404, {"error": "unknown hypothesis",
                             "hypothesis_id": str(fb.hypothesis_id)})
            return
        self._json(201, {"recorded": True,
                         "hypothesis_id": str(fb.hypothesis_id)})

    @route("GET", "/api/v1/learning")
    def learning_status(self):
        self._json(200, self.app.learning_status())

    # -- elastic fleet (graft-swell) ---------------------------------------

    @route("GET", "/api/v1/fleet")
    def fleet_status(self):
        """Per-mesh tenant placement, per-tenant admitted-rows/s load
        estimates, and the scale/migration history ring — the operator
        surface of the elastic fleet (rca/surge.SurgeServer)."""
        surge = getattr(self.app, "surge", None)
        if surge is None:
            self._json(200, {"enabled": False, "packs": {},
                             "placement": {}, "loads": {},
                             "history": [], "generation": 0,
                             "migrations": 0})
            return
        self._json(200, {"enabled": True, **surge.fleet()})

    # -- traces (observability; new) --------------------------------------

    @route("GET", "/api/v1/traces")
    def traces(self):
        self._json(200, {"spans": TRACER.export(self.query.get("trace_id"))})

    @route("GET", "/api/v1/flight-recorder")
    def flight_recorder(self):
        """graft-scope forensics: the live per-tick flight ring plus the
        last on-disk dump the shield froze (tier transition / recovery)."""
        self._json(200, {
            "records": FLIGHT_RECORDER.snapshot(),
            "dumps": FLIGHT_RECORDER.dumps,
            "last_dump_path": FLIGHT_RECORDER.last_dump_path,
        })

    # -- workflow inspection (the Temporal-UI analog; reference
    # docker-compose.yml:80-92 ships Temporal UI so a human can watch an
    # incident's steps — here the journal IS the history, VERDICT r4 item 8)

    @route("GET", "/api/v1/workflows")
    def list_workflows(self):
        # graft-saga: surface STALLED workflows (failed step / exhausted
        # resume budget on an open incident) so the resumer's blind spot
        # is an operator's first glance, and stamp the gauge while here
        from ..observability import metrics as obs_metrics
        max_resumes = int(getattr(self.app.settings,
                                  "workflow_max_resumes", 5))
        stalled = self.app.db.stalled_workflows(max_resumes=max_resumes)
        obs_metrics.WORKFLOW_STALLED.set(float(len(stalled)))
        stalled_ids = {s["workflow_id"]: s["reason"] for s in stalled}
        workflows = self.app.db.journal_workflows()
        for w in workflows:
            w["stalled"] = w["workflow_id"] in stalled_ids
            if w["stalled"]:
                w["stalled_reason"] = stalled_ids[w["workflow_id"]]
        self._json(200, {"workflows": workflows,
                         "stalled": stalled})

    @route("GET", r"/api/v1/workflows/(?P<workflow_id>[A-Za-z0-9_.:-]+)")
    def workflow_timeline(self, workflow_id: str):
        from ..workflow.incident_workflow import STEP_NAMES
        journal = self.app.db.journal_get(workflow_id)
        if not journal:
            return self._json(404, {"error": f"no journal for {workflow_id}"})
        order = [s for s in STEP_NAMES if s in journal] + \
                [s for s in journal if s not in STEP_NAMES]
        steps = [{"step": s, **journal[s]} for s in order]
        failed = [s["step"] for s in steps if s["status"] == "failed"]
        running = [s["step"] for s in steps if s["status"] == "running"]
        done = [s["step"] for s in steps if s["status"] == "completed"]
        self._json(200, {
            "workflow_id": workflow_id,
            # the ONE shared precedence encoding — do not inline it here
            # (it drifted once; ADVICE r5)
            "state": self.app.db.rollup_state(
                len(failed), len(running), len(done)),
            "total_duration_s": sum(s["duration_s"] or 0.0 for s in steps),
            "steps": steps,
        })

    @route("GET", "/workflows")
    def workflows_page(self):
        self._text(200, _WORKFLOWS_HTML, "text/html; charset=utf-8")


# One static self-contained page over the two JSON endpoints above: list on
# the left, per-step timeline (status, attempts, duration bar) on the right.
_WORKFLOWS_HTML = """<!doctype html>
<html><head><meta charset="utf-8"><title>Workflows</title>
<style>
 body{font:14px/1.45 system-ui,sans-serif;margin:0;display:flex;height:100vh}
 #list{width:340px;overflow:auto;border-right:1px solid #ddd;padding:12px}
 #detail{flex:1;overflow:auto;padding:16px 24px}
 h1{font-size:16px;margin:0 0 10px}
 .wf{padding:8px 10px;border-radius:6px;cursor:pointer;margin-bottom:4px}
 .wf:hover{background:#f2f4f7}.wf.sel{background:#e8eefb}
 .wf .id{font-family:ui-monospace,monospace;font-size:12px;word-break:break-all}
 .badge{display:inline-block;padding:1px 8px;border-radius:10px;font-size:11px;
        color:#fff;margin-left:6px;vertical-align:middle}
 .completed{background:#2e7d32}.failed{background:#c62828}
 .running{background:#1565c0}.pending{background:#757575}
 .skipped{background:#9e9e9e}
 table{border-collapse:collapse;width:100%;margin-top:10px}
 td,th{text-align:left;padding:6px 10px;border-bottom:1px solid #eee;
       vertical-align:top}
 .bar{height:8px;background:#1565c0;border-radius:4px;min-width:2px}
 .dur{font-variant-numeric:tabular-nums;white-space:nowrap}
 pre{background:#f6f8fa;padding:8px;border-radius:6px;max-height:160px;
     overflow:auto;font-size:11px;margin:4px 0 0}
 .muted{color:#888}
</style></head><body>
<div id="list"><h1>Workflows</h1><div id="rows" class="muted">loading…</div></div>
<div id="detail"><h1 id="dt">Select a workflow</h1><div id="steps"></div></div>
<script>
const esc = s => String(s).replace(/[&<>"]/g,
  c => ({'&':'&amp;','<':'&lt;','>':'&gt;','"':'&quot;'}[c]));
let selected = null;
async function refreshList(){
  const r = await fetch('/api/v1/workflows'); const d = await r.json();
  const rows = document.getElementById('rows'); rows.innerHTML = '';
  if(!d.workflows.length){rows.textContent = 'no workflows yet'; return;}
  for(const w of d.workflows){
    const el = document.createElement('div');
    el.className = 'wf' + (w.workflow_id === selected ? ' sel' : '');
    el.innerHTML = `<span class="id">${esc(w.workflow_id)}</span>` +
      `<span class="badge ${esc(w.state)}">${esc(w.state)}</span>` +
      `<div class="muted">${w.completed}/${w.steps} steps · ` +
      `${(w.total_duration_s||0).toFixed(2)}s · ${esc(w.last_update)}</div>`;
    el.onclick = () => { selected = w.workflow_id; show(w.workflow_id);
                         refreshList(); };
    rows.appendChild(el);
  }
}
async function show(id){
  const r = await fetch('/api/v1/workflows/' + encodeURIComponent(id));
  const d = await r.json();
  document.getElementById('dt').innerHTML = `${esc(id)}` +
    ` <span class="badge ${esc(d.state)}">${esc(d.state)}</span>` +
    ` <span class="muted dur">${d.total_duration_s.toFixed(2)}s total</span>`;
  const max = Math.max(...d.steps.map(s => s.duration_s || 0), 1e-9);
  let html = '<table><tr><th>step</th><th>status</th><th>attempts</th>' +
             '<th style="width:40%">duration</th><th>updated</th></tr>';
  for(const s of d.steps){
    const w = Math.round(100 * (s.duration_s || 0) / max);
    html += `<tr><td>${esc(s.step)}</td>` +
      `<td><span class="badge ${esc(s.status)}">${esc(s.status)}</span></td>` +
      `<td>${s.attempts}</td>` +
      `<td><div class="bar" style="width:${w}%"></div>` +
      `<span class="muted dur">${s.duration_s == null ? '—'
        : s.duration_s.toFixed(3) + 's'}</span>` +
      (s.result ? `<pre>${esc(JSON.stringify(s.result, null, 1))}</pre>` : '') +
      `</td><td class="muted dur">${esc(s.updated_at || '')}</td></tr>`;
  }
  document.getElementById('steps').innerHTML = html + '</table>';
}
refreshList(); setInterval(() => { refreshList();
  if(selected) show(selected); }, 3000);
</script></body></html>
"""


def make_server(app, host: str = "127.0.0.1", port: int = 0) -> ThreadingHTTPServer:
    handler = type("BoundApiHandler", (ApiHandler,), {"app": app})
    server = ThreadingHTTPServer((host, port), handler)
    server.daemon_threads = True
    return server
