"""graft-intake: vectorized columnar webhook ingest.

The dict path (normalizer.AlertNormalizer) builds one pydantic
IncidentCreate per alert — per-row dict walks, per-row sha256, per-row
timestamp parse. Under an alert storm that is the ingest bottleneck the
flight recorder measured (ROADMAP item 2). This module transposes a whole
webhook batch into NumPy columns in ONE pass over the payload (the
unavoidable JSON→column transpose, ~a dozen dict.gets per alert) and then
derives everything else as array ops over those columns:

* severity mapping, service-label priority (service>app>deployment>job>
  pod-stripped), title fallbacks — np.where chains over object columns;
* fingerprints — the ``source:alertname:namespace:service`` keys are
  composed by elementwise object concatenation and sha256 runs once per
  UNIQUE key (np.unique + inverse take), so a storm of duplicate alerts
  hashes each distinct alert once, not once per row;
* timestamps — parsed once per unique ``startsAt`` string;
* malformed rows (labels not a dict, unparseable timestamp, non-dict
  alert) are MASKED and counted, never raised — one bad row in a batch
  of 10k must not 500 the whole webhook.

pydantic spec construction is deferred to :meth:`ColumnarAlerts.specs`,
which the ingest edge calls only for rows that SURVIVED the (vectorized)
dedup check — the common storm row (a duplicate) never touches pydantic
at all. Row-for-row parity with the dict normalizer is pinned by
tests/test_ingest_columnar.py for all three webhook formats.
"""
from __future__ import annotations

import hashlib
from dataclasses import dataclass, field
from datetime import datetime, timezone
from typing import Iterable

import numpy as np

from ..models import IncidentCreate, IncidentSource, Severity
from ..utils.timeutils import parse_iso, utcnow
from .normalizer import _SEVERITY_MAP

# severity codes: index into this tuple == the int8 column value
_SEVERITY_ORDER: tuple[Severity, ...] = (
    Severity.CRITICAL, Severity.HIGH, Severity.MEDIUM, Severity.LOW,
    Severity.INFO)
_SEVERITY_CODE = {s: i for i, s in enumerate(_SEVERITY_ORDER)}
_DEFAULT_SEV_CODE = _SEVERITY_CODE[Severity.MEDIUM]

# vectorized helpers over object columns (C-driven elementwise loops —
# no per-row Python frames in the caller)
_LEN = np.frompyfunc(len, 1, 1)
_TRUNC500 = np.frompyfunc(lambda s: s[:500], 1, 1)

_TS_MISSING = np.nan          # started_unix sentinel: fall back to utcnow()


def _strip_pod(name: str) -> str:
    """Reference pod→service stripping (normalizer._service_from)."""
    parts = name.rsplit("-", 2)
    return parts[0] if len(parts) == 3 else name


def _obj(n: int, fill: str = "") -> np.ndarray:
    col = np.empty(n, dtype=object)
    col[:] = fill
    return col


@dataclass
class ColumnarAlerts:
    """One webhook batch, transposed: parallel columns over the rows.

    ``valid`` masks malformed rows out of every downstream consumer;
    ``firing`` carries the Alertmanager status filter (grafana payloads
    set it True everywhere — the dict path ingests them regardless of
    status, parity preserved). String columns are object arrays with
    ``""`` for absent-or-empty (``or``-semantics fields); fields whose
    dict-path default is resolved by ``dict.get`` (namespace, cluster)
    carry the default already applied at transpose time."""

    source: IncidentSource
    valid: np.ndarray                 # bool  [B]
    firing: np.ndarray                # bool  [B]
    fingerprint: np.ndarray           # object[B] 32-hex
    title: np.ndarray                 # object[B]
    description: np.ndarray           # object[B] ("" -> None in specs)
    severity_code: np.ndarray         # int8  [B] index into _SEVERITY_ORDER
    cluster: np.ndarray               # object[B]
    namespace: np.ndarray             # object[B]
    service: np.ndarray               # object[B] ("" -> None in specs)
    started_unix: np.ndarray          # float64[B] epoch s (NaN -> utcnow)
    labels: list                      # per-row label dicts (spec payload)
    annotations: list                 # per-row annotation dicts
    malformed: int = 0
    field_defaults: dict = field(default_factory=dict)

    def __len__(self) -> int:
        return len(self.valid)

    @property
    def eligible(self) -> np.ndarray:
        """Rows the ingest edge should consider: well-formed AND firing."""
        return self.valid & self.firing

    def specs(self, rows: Iterable[int] | np.ndarray | None = None
              ) -> list[IncidentCreate]:
        """Materialize IncidentCreate specs for ``rows`` (default: every
        eligible row). Called AFTER dedup on the hot path, so duplicate
        storm rows never pay pydantic validation."""
        if rows is None:
            rows = np.flatnonzero(self.eligible)
        now = None
        out = []
        for i in rows:
            i = int(i)
            ts = self.started_unix[i]
            if np.isnan(ts):
                if now is None:
                    now = utcnow()
                started = now
            else:
                started = datetime.fromtimestamp(float(ts), tz=timezone.utc)
            out.append(IncidentCreate(
                fingerprint=self.fingerprint[i],
                title=self.title[i],
                description=self.description[i] or None,
                severity=_SEVERITY_ORDER[int(self.severity_code[i])],
                source=self.source,
                cluster=self.cluster[i],
                namespace=self.namespace[i],
                service=self.service[i] or None,
                labels=dict(self.labels[i]),
                annotations=dict(self.annotations[i]),
                started_at=started,
            ))
        return out


def _s(v) -> str:
    """Coerce a raw payload field to str ("" for None): object columns
    must stay uniformly str-typed or np.unique's sort would raise on a
    mixed-type storm row."""
    if isinstance(v, str):
        return v
    return "" if v is None else str(v)


def _transpose(alerts: list, n: int) -> dict:
    """The single pass over the payload: raw fields → object columns.
    Defaults resolvable by ``dict.get`` are applied here (namespace /
    cluster); ``or``-semantics fields keep "" so the vectorized
    fallback chains below reproduce the dict path exactly."""
    cols = {
        "valid": np.ones(n, bool),
        "status": _obj(n),
        "alertname": _obj(n),
        "has_alertname": np.zeros(n, bool),
        "namespace": _obj(n, "default"),
        "cluster": _obj(n, "default"),
        "severity_raw": _obj(n),
        "service_l": _obj(n), "app_l": _obj(n), "deploy_l": _obj(n),
        "job_l": _obj(n), "pod_l": _obj(n),
        "summary": _obj(n), "description": _obj(n),
        "starts": _obj(n),
        "labels": [{}] * n, "annotations": [{}] * n,
    }
    for i, alert in enumerate(alerts):
        if not isinstance(alert, dict):
            cols["valid"][i] = False
            continue
        labels = alert.get("labels") or {}
        ann = alert.get("annotations") or {}
        if not isinstance(labels, dict) or not isinstance(ann, dict):
            cols["valid"][i] = False
            continue
        cols["status"][i] = _s(alert.get("status"))
        if "alertname" in labels:
            cols["has_alertname"][i] = True
            cols["alertname"][i] = _s(labels["alertname"])
        cols["namespace"][i] = _s(labels.get("namespace", "default"))
        cols["cluster"][i] = _s(labels.get("cluster", "default"))
        cols["severity_raw"][i] = _s(labels.get("severity"))
        cols["service_l"][i] = _s(labels.get("service"))
        cols["app_l"][i] = _s(labels.get("app"))
        cols["deploy_l"][i] = _s(labels.get("deployment"))
        cols["job_l"][i] = _s(labels.get("job"))
        cols["pod_l"][i] = _s(labels.get("pod"))
        cols["summary"][i] = _s(ann.get("summary"))
        cols["description"][i] = _s(ann.get("description"))
        cols["starts"][i] = _s(alert.get("startsAt"))
        cols["labels"][i] = labels
        cols["annotations"][i] = ann
    return cols


def _map_unique(col: np.ndarray, fn) -> np.ndarray:
    """Apply ``fn`` once per UNIQUE value of an object column and
    broadcast back — the storm-shaped transform (duplicate-heavy columns
    pay O(unique), not O(rows))."""
    uniq, inv = np.unique(col, return_inverse=True)
    mapped = np.empty(len(uniq), dtype=object)
    mapped[:] = [fn(u) for u in uniq]
    return mapped[inv]


def _severity_codes(raw: np.ndarray) -> np.ndarray:
    uniq, inv = np.unique(raw, return_inverse=True)
    codes = np.array(
        [_SEVERITY_CODE.get(_SEVERITY_MAP.get(str(u).lower()),
                            _DEFAULT_SEV_CODE) for u in uniq],
        dtype=np.int8)
    return codes[inv]


def _fingerprints(source: str, alertname: np.ndarray, namespace: np.ndarray,
                  service: np.ndarray) -> np.ndarray:
    """sha256 once per unique (alertname, namespace, service) key.
    Identical to utils.hashing.alert_fingerprint row for row."""
    keys = (source + ":") + alertname + (":" + namespace) + (":" + service)
    return _map_unique(
        keys, lambda k: hashlib.sha256(str(k).encode()).hexdigest()[:32])


def _timestamps(starts: np.ndarray, valid: np.ndarray
                ) -> tuple[np.ndarray, int]:
    """Parse once per unique startsAt; unparseable rows are masked out of
    ``valid`` (in place) and counted, not raised."""
    uniq, inv = np.unique(starts, return_inverse=True)
    epoch = np.empty(len(uniq), np.float64)
    bad = np.zeros(len(uniq), bool)
    for j, u in enumerate(uniq):
        if not u:
            epoch[j] = _TS_MISSING
            continue
        try:
            epoch[j] = parse_iso(str(u)).timestamp()
        except (ValueError, TypeError):
            epoch[j] = _TS_MISSING
            bad[j] = True
    bad_rows = bad[inv] & valid
    valid &= ~bad_rows
    return epoch[inv], int(bad_rows.sum())


def _derive(cols: dict, source: IncidentSource, n: int,
            fallback_title: str = "", fallback_desc: str = "",
            fp_alertname_default: str = "") -> ColumnarAlerts:
    """Array-op derivations over the transposed columns — the vectorized
    twin of AlertNormalizer's per-row logic."""
    valid = cols["valid"]
    started, ts_bad = _timestamps(cols["starts"], valid)
    malformed = int((~valid).sum())

    # service priority chain; pod names stripped per unique pod
    pod_svc = _map_unique(cols["pod_l"], _strip_pod)
    service = np.where(
        cols["service_l"] != "", cols["service_l"],
        np.where(cols["app_l"] != "", cols["app_l"],
                 np.where(cols["deploy_l"] != "", cols["deploy_l"],
                          np.where(cols["job_l"] != "", cols["job_l"],
                                   pod_svc))))

    # title: summary[:500] if present, else "alertname: subject" / alertname
    subject = np.where(
        cols["pod_l"] != "", cols["pod_l"],
        np.where(cols["deploy_l"] != "", cols["deploy_l"],
                 cols["service_l"]))
    named = np.where(cols["alertname"] != "", cols["alertname"],
                     "UnknownAlert")
    title = np.where(
        cols["summary"] != "", _TRUNC500(cols["summary"]),
        np.where(subject != "", named + ": " + subject, named))
    if fallback_title:
        # grafana: alerts with NO labels fall back to the payload title
        has_labels = np.array([bool(l) for l in cols["labels"]], bool)
        title = np.where(has_labels, title, fallback_title[:500])

    description = cols["description"]
    if fallback_desc:
        description = np.where(description != "", description, fallback_desc)

    fp_alertname = cols["alertname"]
    if fp_alertname_default:
        # grafana fingerprints default a MISSING alertname label to the
        # payload title (dict.get default semantics: present-empty stays "")
        fp_alertname = np.where(cols["has_alertname"], fp_alertname,
                                fp_alertname_default)
    fingerprint = _fingerprints(source.value, fp_alertname,
                                cols["namespace"], service)

    firing = (cols["status"] == "firing") \
        if source is not IncidentSource.GRAFANA else np.ones(n, bool)

    return ColumnarAlerts(
        source=source,
        valid=valid,
        firing=firing,
        fingerprint=fingerprint,
        title=title,
        description=description,
        severity_code=_severity_codes(cols["severity_raw"]),
        cluster=cols["cluster"],
        namespace=cols["namespace"],
        service=service,
        started_unix=started,
        labels=cols["labels"],
        annotations=cols["annotations"],
        malformed=malformed,
    )


def normalize_alertmanager_batch(alerts: list) -> ColumnarAlerts:
    """Columnar twin of AlertNormalizer.normalize_alertmanager over a
    whole webhook batch. Non-firing rows stay in the columns with
    ``firing=False`` (the handler's status filter, vectorized)."""
    n = len(alerts)
    return _derive(_transpose(alerts, n), IncidentSource.ALERTMANAGER, n)


def normalize_prometheus_batch(alerts: list) -> ColumnarAlerts:
    """Columnar twin of AlertNormalizer.normalize_prometheus (alertmanager
    shape, prometheus fingerprint source)."""
    n = len(alerts)
    return _derive(_transpose(alerts, n), IncidentSource.PROMETHEUS, n)


def normalize_grafana_batch(payload: dict) -> ColumnarAlerts:
    """Columnar twin of AlertNormalizer.normalize_grafana: multi-alert
    payloads with payload-level title/message fallbacks; no status
    filter (parity with the dict path, which ingests every row)."""
    alerts = payload.get("alerts", []) or []
    if not isinstance(alerts, list):
        alerts = []
    n = len(alerts)
    return _derive(
        _transpose(alerts, n), IncidentSource.GRAFANA, n,
        fallback_title=(payload.get("title") or "Grafana alert"),
        fallback_desc=(payload.get("message") or ""),
        fp_alertname_default=payload.get("title", ""))
