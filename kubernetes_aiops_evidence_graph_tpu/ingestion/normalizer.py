"""Alert normalization: Alertmanager / Grafana / Prometheus payloads →
IncidentCreate.

Parity with the reference AlertNormalizer (normalizer.py:15-218): the same
severity map, title/cluster/service extraction order from labels, and the
sha256 fingerprint over source:alertname:namespace:service (:208-218, via
utils.hashing.alert_fingerprint).
"""
from __future__ import annotations

from typing import Any

from ..models import IncidentCreate, IncidentSource, Severity
from ..utils.hashing import alert_fingerprint
from ..utils.timeutils import parse_iso, utcnow

_SEVERITY_MAP = {
    "critical": Severity.CRITICAL,
    "error": Severity.HIGH,
    "high": Severity.HIGH,
    "warning": Severity.MEDIUM,
    "medium": Severity.MEDIUM,
    "info": Severity.INFO,
    "low": Severity.LOW,
    "none": Severity.INFO,
}


def _severity(raw: str | None) -> Severity:
    return _SEVERITY_MAP.get((raw or "").lower(), Severity.MEDIUM)


def _service_from(labels: dict[str, str]) -> str | None:
    for key in ("service", "app", "deployment", "job", "pod"):
        if labels.get(key):
            val = labels[key]
            if key == "pod":  # strip replicaset/pod suffixes
                parts = val.rsplit("-", 2)
                return parts[0] if len(parts) == 3 else val
            return val
    return None


def _title_from(labels: dict[str, str], annotations: dict[str, str]) -> str:
    alertname = labels.get("alertname", "UnknownAlert")
    subject = labels.get("pod") or labels.get("deployment") or labels.get("service")
    if annotations.get("summary"):
        return annotations["summary"][:500]
    return f"{alertname}: {subject}" if subject else alertname


class AlertNormalizer:
    """Classmethod-style API matching the reference normalizer."""

    @classmethod
    def normalize_alertmanager(cls, alert: dict[str, Any]) -> IncidentCreate:
        labels = alert.get("labels", {}) or {}
        annotations = alert.get("annotations", {}) or {}
        namespace = labels.get("namespace", "default")
        service = _service_from(labels)
        started = alert.get("startsAt")
        return IncidentCreate(
            fingerprint=alert_fingerprint(
                "alertmanager", labels.get("alertname", ""), namespace, service),
            title=_title_from(labels, annotations),
            description=annotations.get("description"),
            severity=_severity(labels.get("severity")),
            source=IncidentSource.ALERTMANAGER,
            cluster=labels.get("cluster", "default"),
            namespace=namespace,
            service=service,
            labels=dict(labels),
            annotations=dict(annotations),
            started_at=parse_iso(started) if started else utcnow(),
        )

    @classmethod
    def normalize_grafana(cls, payload: dict[str, Any]) -> list[IncidentCreate]:
        out = []
        for alert in payload.get("alerts", []) or []:
            labels = alert.get("labels", {}) or {}
            annotations = alert.get("annotations", {}) or {}
            namespace = labels.get("namespace", "default")
            service = _service_from(labels)
            started = alert.get("startsAt")
            out.append(IncidentCreate(
                fingerprint=alert_fingerprint(
                    "grafana", labels.get("alertname", payload.get("title", "")),
                    namespace, service),
                title=_title_from(labels, annotations) if labels
                else (payload.get("title") or "Grafana alert")[:500],
                description=annotations.get("description") or payload.get("message"),
                severity=_severity(labels.get("severity")),
                source=IncidentSource.GRAFANA,
                cluster=labels.get("cluster", "default"),
                namespace=namespace,
                service=service,
                labels=dict(labels),
                annotations=dict(annotations),
                started_at=parse_iso(started) if started else utcnow(),
            ))
        return out

    @classmethod
    def normalize_prometheus(cls, alert: dict[str, Any]) -> IncidentCreate:
        inc = cls.normalize_alertmanager(alert)
        return IncidentCreate(**{
            **inc.model_dump(),
            "source": IncidentSource.PROMETHEUS,
            "fingerprint": alert_fingerprint(
                "prometheus", inc.labels.get("alertname", ""),
                inc.namespace, inc.service),
        })
