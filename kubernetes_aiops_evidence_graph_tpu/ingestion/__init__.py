from .dedup import AlertDeduplicator, RateLimiter, TTLSet
from .normalizer import AlertNormalizer

__all__ = ["AlertNormalizer", "AlertDeduplicator", "RateLimiter", "TTLSet"]
