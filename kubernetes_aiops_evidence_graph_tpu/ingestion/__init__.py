from .admission import AdmissionController, CircuitBreaker, StormMode
from .columnar import (
    ColumnarAlerts,
    normalize_alertmanager_batch,
    normalize_grafana_batch,
    normalize_prometheus_batch,
)
from .dedup import AlertDeduplicator, FingerprintRing, RateLimiter, TTLSet
from .normalizer import AlertNormalizer

__all__ = [
    "AlertNormalizer", "AlertDeduplicator", "RateLimiter", "TTLSet",
    "FingerprintRing", "ColumnarAlerts", "normalize_alertmanager_batch",
    "normalize_grafana_batch", "normalize_prometheus_batch",
    "AdmissionController", "CircuitBreaker", "StormMode",
]
