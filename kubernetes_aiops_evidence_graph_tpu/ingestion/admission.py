"""graft-storm: overload-robust admission for the webhook→verdict path.

graft-intake proved the columnar ingest path is FAST (10k ev/s paced,
~38k unpaced); this module makes it survive being asked for 5× that.
Industrial RCA (Groot, PAPERS.md) lives or dies during alert storms and
grey failures: inflow spikes 10–100×, and the one unacceptable behavior
is dropping the critical signal while drowning in the noise. Three
pieces, all host-side (nothing here touches jitted code — COST_BASELINE
is untouched by construction):

1. **Per-tenant token-bucket admission** (:class:`AdmissionController`).
   Replaces the fixed-window ``RateLimiter`` on the columnar path — the
   fixed window admits 2× bursts across window boundaries and knows
   nothing about severity or tenancy. The gate charges tokens only for
   dedup SURVIVORS (duplicates ride free: the ring absorbs them before
   the gate, so a duplicate-heavy storm cannot shed the critical
   needle), sheds lowest-severity-first when the bucket runs dry, and
   NEVER sheds critical — a critical row admits even at zero tokens
   (bounded overdraft). Buckets are per tenant, so one misbehaving
   tenant's storm cannot starve its neighbors — the same isolation
   contract graft-surge gives the packed serving regions. Shed requests
   carry ``Retry-After`` derived from the bucket refill time.

2. **Storm mode** (:class:`StormMode`). A hysteresis-gated degraded tier:
   sustained pressure (admission shed ratio, dedup-ring eviction rate,
   or absorb busy-yield rate over their thresholds for a dwell) enters;
   sustained calm exits. While active: the gate pre-sheds ``info`` rows
   even with tokens remaining, app.ingest_batch samples persistence of
   presumed re-arrivals past an evicting ring, and the serving executor
   coalesces harder (rca/streaming.py reads the
   ``observability.scope.STORM_FLAG`` mirror — the ingest and serving
   layers share the flag without an import edge). Transitions are
   counted, note_event'd into the flight ring, and every tick dispatched
   during storm carries a ``storm`` flag in its TickSpan.

3. **Circuit breakers** (:class:`CircuitBreaker`). Bounded-failure-count
   → open → half-open probe around the two blocking downstreams: SQLite
   persist (app.py — open degrades ingest to the bounded spill journal)
   and device dispatch (rca/shield.py — open degrades tick()/absorb()
   to journal-only until the probe recovers). A wedged downstream costs
   one state check per webhook instead of a timeout per webhook.

Everything is deterministic given the injected clock — the chaos tests
drive all three pieces from fake clocks and seeded fault schedules.
"""
from __future__ import annotations

import threading
import time

import numpy as np

from ..config import Settings, get_settings
from ..observability import get_logger
from ..observability import metrics as obs_metrics
from ..observability import scope as obs_scope

log = get_logger("admission")

# severity codes are indexes into columnar._SEVERITY_ORDER:
# 0=critical 1=high 2=medium 3=low 4=info. Shedding walks codes
# DESCENDING (info first), and code 0 is never shed.
_CRITICAL_CODE = 0

# prune admission buckets idle longer than this when the tenant table
# grows past _MAX_TENANTS — the RateLimiter._windows leak class, fixed
# structurally here rather than discovered in production
_BUCKET_IDLE_S = 300.0
_MAX_TENANTS = 4096


class CircuitBreaker:
    """Bounded-failure-count circuit breaker: ``closed`` → (N consecutive
    failures) → ``open`` → (cooldown) → ``half_open`` (exactly one probe)
    → ``closed`` on success / ``open`` on failure.

    ``allow()`` answers "may I attempt the protected operation now":
    closed always, open never until the cooldown elapses, half-open for
    exactly one in-flight probe. State changes are counted in
    ``aiops_breaker_transitions_total`` and mirrored to the
    ``aiops_breaker_state`` gauge.
    """

    _STATE_CODE = {"closed": 0.0, "half_open": 1.0, "open": 2.0}

    def __init__(self, name: str, failure_threshold: int = 5,
                 cooldown_s: float = 2.0, clock=time.monotonic) -> None:
        self.name = name
        self.failure_threshold = max(int(failure_threshold), 1)
        self.cooldown_s = float(cooldown_s)
        self._clock = clock
        self._lock = threading.Lock()
        self.state = "closed"
        self.failures = 0
        self.opened_at = 0.0
        self.opens = 0
        self._probe_inflight = False
        obs_metrics.BREAKER_STATE.set(0.0, breaker=name)

    def _set_state(self, state: str) -> None:
        """Caller holds the lock."""
        if state == self.state:
            return
        self.state = state
        obs_metrics.BREAKER_STATE.set(self._STATE_CODE[state],
                                      breaker=self.name)
        obs_metrics.BREAKER_TRANSITIONS.inc(breaker=self.name, state=state)
        log.warning("breaker_transition", breaker=self.name, state=state,
                    failures=self.failures)

    def allow(self) -> bool:
        with self._lock:
            if self.state == "closed":
                return True
            if self.state == "open":
                if self._clock() - self.opened_at < self.cooldown_s:
                    return False
                self._set_state("half_open")
                self._probe_inflight = True
                return True
            # half_open: one probe at a time
            if self._probe_inflight:
                return False
            self._probe_inflight = True
            return True

    def record_success(self) -> None:
        with self._lock:
            self.failures = 0
            self._probe_inflight = False
            if self.state != "closed":
                self._set_state("closed")

    def record_failure(self) -> None:
        with self._lock:
            self.failures += 1
            self._probe_inflight = False
            if self.state == "half_open" or (
                    self.state == "closed"
                    and self.failures >= self.failure_threshold):
                self.opened_at = self._clock()
                self.opens += 1
                self._set_state("open")

    def reset(self) -> None:
        self.record_success()

    def stats(self) -> dict:
        with self._lock:
            return {"name": self.name, "state": self.state,
                    "failures": self.failures, "opens": self.opens}


class StormMode:
    """Hysteresis-gated storm tier. ``update(hi, lo)`` feeds one pressure
    observation: ``hi`` is the ENTER predicate (pressure over the enter
    thresholds), ``lo`` the stay-degraded predicate (over the lower exit
    thresholds). Sustained ``hi`` for ``dwell_s`` enters; sustained
    ``not lo`` for ``dwell_s`` exits — the classic two-threshold + dwell
    gate, so a flapping signal cannot flap the tier.

    Transitions mirror into ``observability.scope.STORM_FLAG`` (the
    serving layer's read side), the ``aiops_storm_mode`` gauge, the
    transition counter, and a flight-recorder event — storm entry/exit
    is stamped into the same forensic stream as shield tier changes.
    """

    def __init__(self, settings: "Settings | None" = None,
                 clock=time.monotonic) -> None:
        s = settings or get_settings()
        self.dwell_s = float(getattr(s, "storm_dwell_s", 1.0))
        self._clock = clock
        self._lock = threading.Lock()
        self.active = False
        self.entries = 0
        self.exits = 0
        self._hi_since: float | None = None
        self._calm_since: float | None = None
        obs_scope.STORM_FLAG["active"] = False
        obs_metrics.STORM_MODE.set(0.0)

    def update(self, hi: bool, lo: bool | None = None) -> bool:
        """Feed one observation; returns the (possibly new) active state."""
        lo = hi if lo is None else lo
        now = self._clock()
        with self._lock:
            if not self.active:
                self._hi_since = (self._hi_since or now) if hi else None
                if hi and now - self._hi_since >= self.dwell_s:
                    self._flip(True, now)
            else:
                self._calm_since = ((self._calm_since or now)
                                    if not lo else None)
                if not lo and now - self._calm_since >= self.dwell_s:
                    self._flip(False, now)
            return self.active

    def force(self, active: bool) -> None:
        """Test/bench seam: set the tier directly (still counted)."""
        now = self._clock()
        with self._lock:
            if active != self.active:
                self._flip(active, now)

    def _flip(self, active: bool, now: float) -> None:
        """Caller holds the lock."""
        self.active = active
        self._hi_since = None
        self._calm_since = None
        if active:
            self.entries += 1
        else:
            self.exits += 1
        obs_scope.STORM_FLAG["active"] = active
        obs_metrics.STORM_MODE.set(1.0 if active else 0.0)
        obs_metrics.STORM_TRANSITIONS.inc(
            direction="enter" if active else "exit")
        obs_scope.FLIGHT_RECORDER.note_event(
            "storm_mode", active=active,
            entries=self.entries, exits=self.exits)
        log.warning("storm_mode_transition", active=active)


class _Bucket:
    __slots__ = ("tokens", "last")

    def __init__(self, tokens: float, last: float) -> None:
        self.tokens = tokens
        self.last = last


class AdmissionController:
    """Per-tenant token-bucket admission gate with severity-weighted
    shedding (see module docstring for the policy). One instance per
    app; ``admit_batch`` is the only hot call — a handful of NumPy ops
    per webhook batch plus a dict lookup per tenant."""

    def __init__(self, settings: "Settings | None" = None,
                 clock=time.monotonic, injector=None,
                 storm: "StormMode | None" = None) -> None:
        self.settings = settings or get_settings()
        self.rate = max(float(getattr(self.settings,
                                      "admission_rate_per_sec", 2000.0)),
                        1e-6)
        self.burst = max(float(getattr(self.settings,
                                       "admission_burst", 4000.0)), 1.0)
        self._clock = clock
        self.injector = injector
        self.storm = storm if storm is not None else StormMode(
            self.settings, clock=clock)
        self._lock = threading.Lock()
        self._buckets: dict[str, _Bucket] = {}
        self.admitted = 0
        self.shed = 0
        self.shed_by_severity: dict[int, int] = {}
        # storm pressure signals: EWMA shed ratio + metric-counter deltas
        self._shed_ewma = 0.0
        self._last_signal_t = self._clock()
        self._last_evictions = obs_metrics.INGEST_DEDUP_EVICTIONS.value()
        self._last_busy = obs_metrics.SERVE_ABSORB_BUSY.value()

    # -- bucket mechanics --------------------------------------------------

    def _bucket(self, tenant: str, now: float) -> _Bucket:
        """Caller holds the lock. Refills and returns the tenant bucket;
        prunes idle buckets when the table outgrows the cap (the
        fixed-window limiter's per-client leak, fixed structurally)."""
        b = self._buckets.get(tenant)
        if b is None:
            if len(self._buckets) >= _MAX_TENANTS:
                stale = [t for t, bb in self._buckets.items()
                         if now - bb.last > _BUCKET_IDLE_S]
                for t in stale:
                    del self._buckets[t]
            b = self._buckets[tenant] = _Bucket(self.burst, now)
        else:
            b.tokens = min(self.burst, b.tokens + (now - b.last) * self.rate)
            b.last = now
        return b

    def retry_after_s(self, tenant: str) -> float:
        """Seconds until the tenant's bucket refills to one token — the
        Retry-After a shed response carries."""
        now = self._clock()
        with self._lock:
            b = self._bucket(tenant, now)
            if b.tokens >= 1.0:
                return 0.0
            return (1.0 - b.tokens) / self.rate

    # -- the gate ----------------------------------------------------------

    def admit_batch(self, tenants: np.ndarray, severity_codes: np.ndarray,
                    chargeable: "np.ndarray | None" = None
                    ) -> tuple[np.ndarray, float]:
        """[B] admit mask for one webhook batch.

        ``tenants``/``severity_codes`` are the columnar namespace and
        int8 severity columns for the rows under consideration;
        ``chargeable`` masks the rows that actually consume drain
        capacity (dedup survivors — duplicate rows are always "admitted"
        here in the sense that the gate does not shed them; the ring
        already suppressed them). Within one tenant, chargeable rows are
        considered in ascending severity-code order (critical first), so
        when the bucket runs dry the shed set is exactly the
        lowest-severity tail — info sheds before low before medium
        before high, and critical NEVER sheds (it admits on overdraft,
        bounded at -burst). Returns ``(admit_mask, retry_after_s)`` with
        ``retry_after_s`` > 0 iff anything was shed."""
        if self.injector is not None:
            self.injector.at("admit")
        n = len(severity_codes)
        admit = np.ones(n, bool)
        if n == 0:
            self._signal(0, 0)
            return admit, 0.0
        sev = np.asarray(severity_codes)
        charge = (np.ones(n, bool) if chargeable is None
                  else np.asarray(chargeable, bool))
        storm_active = self.storm.active
        now = self._clock()
        retry_after = 0.0
        shed_rows = 0
        charged_rows = int(charge.sum())
        with self._lock:
            tcol = np.asarray(tenants, dtype=object)
            for tenant in np.unique(tcol[charge]) if charged_rows else ():
                rows = np.flatnonzero((tcol == tenant) & charge)
                b = self._bucket(str(tenant), now)
                tenant_shed = 0
                # ascending severity code = admit critical first; stable
                # sort keeps arrival order within one severity
                order = rows[np.argsort(sev[rows], kind="stable")]
                for r in order:
                    code = int(sev[r])
                    if code == _CRITICAL_CODE:
                        # NEVER shed: overdraft, bounded at -burst
                        b.tokens = max(b.tokens - 1.0, -self.burst)
                        continue
                    if b.tokens >= 1.0 and not (storm_active
                                                and code >= 4):
                        # storm tier pre-sheds info (code 4) outright:
                        # the degraded tier keeps headroom for the
                        # severities that page someone
                        b.tokens -= 1.0
                        continue
                    admit[r] = False
                    tenant_shed += 1
                    self.shed_by_severity[code] = \
                        self.shed_by_severity.get(code, 0) + 1
                    obs_metrics.ADMISSION_SHED.inc(
                        tenant=str(tenant), severity=str(code))
                if tenant_shed:
                    # Retry-After only means something when this batch
                    # actually shed: time for the dry bucket to refill
                    # to one token
                    shed_rows += tenant_shed
                    retry_after = max(
                        retry_after,
                        max(1.0 - b.tokens, 0.0) / self.rate)
                obs_metrics.ADMISSION_TOKENS.set(b.tokens,
                                                 tenant=str(tenant))
            self.shed += shed_rows
            self.admitted += n - shed_rows
        # admitted counters outside the lock (label fan-out is bounded)
        adm = admit & charge
        if adm.any():
            for tenant in np.unique(tcol[adm]):
                trows = (tcol == tenant) & adm
                for code in np.unique(sev[trows]):
                    obs_metrics.ADMISSION_ADMITTED.inc(
                        float(int((sev[trows] == code).sum())),
                        tenant=str(tenant), severity=str(int(code)))
        self._signal(shed_rows, charged_rows)
        return admit, retry_after

    # -- storm pressure ----------------------------------------------------

    def _signal(self, shed_rows: int, charged_rows: int) -> None:
        """Fold one batch's shed ratio plus the ring-eviction and
        absorb-busy counter rates into the storm hysteresis."""
        s = self.settings
        ratio = shed_rows / charged_rows if charged_rows else 0.0
        now = self._clock()
        with self._lock:
            self._shed_ewma = 0.8 * self._shed_ewma + 0.2 * ratio
            dt = max(now - self._last_signal_t, 1e-6)
            ev = obs_metrics.INGEST_DEDUP_EVICTIONS.value()
            busy = obs_metrics.SERVE_ABSORB_BUSY.value()
            ev_rate = (ev - self._last_evictions) / dt
            busy_rate = (busy - self._last_busy) / dt
            self._last_signal_t = now
            self._last_evictions = ev
            self._last_busy = busy
            ewma = self._shed_ewma
        enter = float(getattr(s, "storm_enter_shed_ratio", 0.25))
        exit_ = float(getattr(s, "storm_exit_shed_ratio", 0.02))
        ev_thr = float(getattr(s, "storm_eviction_rate_per_s", 500.0))
        busy_thr = float(getattr(s, "storm_busy_rate_per_s", 50.0))
        hi = (ewma > enter or ev_rate > ev_thr or busy_rate > busy_thr)
        lo = (ewma > exit_ or ev_rate > ev_thr / 2.0
              or busy_rate > busy_thr / 2.0)
        self.storm.update(hi, lo)

    def stats(self) -> dict:
        with self._lock:
            return {
                "admitted": self.admitted,
                "shed": self.shed,
                "shed_by_severity": dict(self.shed_by_severity),
                # contract surface: stays 0 forever by construction (the
                # gate admits code 0 on overdraft) — asserted by the
                # webhook_storm bench and the graft-storm CI job
                "critical_shed": self.shed_by_severity.get(
                    _CRITICAL_CODE, 0),
                "shed_ewma": round(self._shed_ewma, 4),
                "storm_active": self.storm.active,
                "storm_entries": self.storm.entries,
                "storm_exits": self.storm.exits,
                "tenants": len(self._buckets),
            }
