from .engine import (
    ALLOWED_ACTIONS,
    HIGH_RISK_ACTIONS,
    PROTECTED_NAMESPACES,
    PolicyEngine,
    PolicyInput,
    PolicyResult,
    evaluate,
)

__all__ = [
    "PolicyEngine", "PolicyInput", "PolicyResult", "evaluate",
    "ALLOWED_ACTIONS", "HIGH_RISK_ACTIONS", "PROTECTED_NAMESPACES",
]
