"""In-process remediation policy engine.

Evaluates the exact semantics of the reference's Rego policy
(src/services/policy/policies/remediation.rego:1-167) without an external
OPA server: per-environment action allowlists (:27-49), a high-risk set
that is never auto-allowed (:52-59), freeze windows — 22:00-06:00 local,
prod weekends, explicit flag (:62-80) — blast-radius thresholds with dev
exemption and the staging <75 carve-out (:83-95), protected namespaces with
dev exemption (:98-113), the conjunctive allow rule (:116-121), the
requires-approval rules (:124-143), and the denial reasons (:146-166).

Unlike the reference's OPA client (opa_client.py:79-87) there is no network
call to fail — but evaluation errors still fail closed.
"""
from __future__ import annotations

from dataclasses import dataclass, field
from datetime import datetime

from ..utils.timeutils import utcnow

ALLOWED_ACTIONS = {
    "dev": {"restart_pod", "delete_pod", "restart_deployment",
            "rollback_deployment", "scale_replicas", "cordon_node"},
    "staging": {"restart_pod", "delete_pod", "restart_deployment",
                "scale_replicas", "rollback_deployment"},
    "prod": {"restart_pod", "delete_pod", "restart_deployment", "scale_replicas"},
}

HIGH_RISK_ACTIONS = {
    "drain_node", "delete_pvc", "update_resource_limits",
    "delete_namespace", "update_configmap", "uncordon_node",
}

PROTECTED_NAMESPACES = {
    "kube-system", "kube-public", "kube-node-lease",
    "istio-system", "cert-manager", "monitoring",
}


@dataclass(frozen=True)
class PolicyInput:
    """Mirror of the OPA input document (opa_client.py:42-53)."""
    action_type: str
    environment: str            # dev|staging|uat|prod
    blast_radius_score: float
    namespace: str
    affected_replicas: int = 1
    current_hour: int | None = None
    is_weekend: bool | None = None
    freeze_active: bool = False
    now: datetime | None = None

    def resolved_hour(self) -> int:
        if self.current_hour is not None:
            return self.current_hour
        return (self.now or utcnow()).hour

    def resolved_weekend(self) -> bool:
        if self.is_weekend is not None:
            return self.is_weekend
        return (self.now or utcnow()).weekday() >= 5


@dataclass
class PolicyResult:
    allow: bool
    requires_approval: bool
    deny_reasons: list[str] = field(default_factory=list)

    @property
    def reason(self) -> str | None:
        return "; ".join(self.deny_reasons) if self.deny_reasons else None


def in_freeze_window(p: PolicyInput) -> bool:
    hour = p.resolved_hour()
    if hour >= 22 or hour < 6:            # late-night freeze (:62-69)
        return True
    if p.environment == "prod" and p.resolved_weekend():  # :71-75
        return True
    return p.freeze_active                # :77-80


def env_allows_action(p: PolicyInput) -> bool:
    allowed = ALLOWED_ACTIONS.get(p.environment)
    if allowed is None:                   # uat & unknown envs have no allowlist
        return False
    if p.action_type not in allowed:
        return False
    if p.environment in ("staging", "prod") and in_freeze_window(p):
        return False                      # dev is exempt from freezes (:9-12)
    return True


def blast_radius_ok(p: PolicyInput) -> bool:
    if p.environment == "dev":            # :88-90
        return True
    if p.environment == "staging" and p.blast_radius_score < 75:  # :92-95
        return True
    return p.blast_radius_score < 50 and p.affected_replicas < 5  # :83-86


def namespace_allowed(p: PolicyInput) -> bool:
    if p.environment == "dev":            # :102-104
        return True
    return p.namespace not in PROTECTED_NAMESPACES


def requires_approval(p: PolicyInput) -> bool:
    return (
        p.environment == "prod"                                   # :124-126
        or (p.environment == "staging" and p.blast_radius_score >= 30)  # :128-131
        or p.action_type == "rollback_deployment"                 # :133-135
        or p.action_type == "cordon_node"                         # :137-139
        or p.affected_replicas >= 3                               # :141-143
    )


def evaluate(p: PolicyInput) -> PolicyResult:
    try:
        env_ok = env_allows_action(p)
        allow = (
            env_ok
            and blast_radius_ok(p)
            and namespace_allowed(p)
            and p.action_type not in HIGH_RISK_ACTIONS
        )
        reasons: list[str] = []
        if not env_ok:
            # every env-level deny carries its own cause, independent of
            # whether namespace/blast checks below also fail — the reference
            # Rego leaves a plain allowlist miss (e.g. cordon_node in prod
            # outside a freeze) reasonless (remediation.rego:146-166 has no
            # rule for it); that is a gap we fix rather than replicate
            env_explained = False
            if p.action_type in HIGH_RISK_ACTIONS:
                reasons.append(
                    f"Action {p.action_type} is high risk and not allowed")
                env_explained = True
            if p.environment in ("staging", "prod") and in_freeze_window(p):
                reasons.append("Action not allowed during freeze window")
                env_explained = True
            if not env_explained:
                if ALLOWED_ACTIONS.get(p.environment) is None:
                    reasons.append(
                        f"Environment {p.environment} has no action allowlist")
                else:
                    reasons.append(
                        f"Action {p.action_type} is not in the"
                        f" {p.environment} allowlist")
        if not namespace_allowed(p):
            reasons.append(f"Namespace {p.namespace} is protected")
        if not blast_radius_ok(p):
            reasons.append(
                f"Blast radius score {p.blast_radius_score} exceeds threshold")
        return PolicyResult(
            allow=allow,
            requires_approval=requires_approval(p),
            deny_reasons=reasons,
        )
    except Exception as exc:  # graft-audit: allow[broad-except] fail closed (opa_client.py:79-87): any evaluation error denies
        return PolicyResult(
            allow=False, requires_approval=True,
            deny_reasons=[f"policy evaluation error: {exc}"])


class PolicyEngine:
    """Object facade matching the reference OPAClient call shape
    (opa_client.py:23-53)."""

    def evaluate_remediation(
        self,
        action_type: str,
        environment: str,
        blast_radius_score: float,
        namespace: str,
        affected_replicas: int = 1,
        freeze_active: bool = False,
        now: datetime | None = None,
    ) -> dict:
        env = {"development": "dev", "production": "prod"}.get(
            environment.lower(), environment.lower())
        result = evaluate(PolicyInput(
            action_type=action_type, environment=env,
            blast_radius_score=blast_radius_score, namespace=namespace,
            affected_replicas=affected_replicas, freeze_active=freeze_active,
            now=now,
        ))
        return {
            "allow": result.allow,
            "requires_approval": result.requires_approval,
            "reason": result.reason,
        }

    def evaluate_compensation(
        self,
        original_action_type: str,
        environment: str,
        namespace: str,
    ) -> dict:
        """graft-saga compensation gate. Compensation RESTORES the
        pre-action state of an action this engine already allowed and an
        approver already signed off on, so the question is not "would the
        inverse action pass as a fresh proposal" (uncordon_node is
        HIGH_RISK and never would) but "is the original action class
        still within this environment's remit". Freeze windows are
        deliberately NOT applied: leaving a failed remediation's mutation
        standing through a freeze is worse than undoing it."""
        env = {"development": "dev", "production": "prod"}.get(
            environment.lower(), environment.lower())
        allowed_set = ALLOWED_ACTIONS.get(env)
        reasons: list[str] = []
        if allowed_set is None:
            reasons.append(f"Environment {env} has no action allowlist")
        elif original_action_type not in allowed_set:
            reasons.append(f"Action {original_action_type} is not in the"
                           f" {env} allowlist")
        if env != "dev" and namespace in PROTECTED_NAMESPACES:
            reasons.append(f"Namespace {namespace} is protected")
        return {
            "allow": not reasons,
            "requires_approval": False,  # covered by the original approval
            "reason": "; ".join(reasons) if reasons else None,
        }
