"""Hypothesis and RCA-result models.

Capability parity with the reference (src/models/hypothesis.py:12-176):
same 11 categories, 4 sources, confidence/rank/score-breakdown fields.
Extended with ``final_score`` (the ranker's output is persisted explicitly
rather than smuggled into a dict) and ``backend`` (cpu|tpu provenance).
"""
from __future__ import annotations

from datetime import datetime
from enum import Enum
from typing import Optional
from uuid import UUID, uuid4

from pydantic import BaseModel, Field

from .incident import utcnow


class HypothesisCategory(str, Enum):
    RESOURCE_EXHAUSTION = "resource_exhaustion"
    BAD_DEPLOYMENT = "bad_deployment"
    CONFIGURATION_ERROR = "configuration_error"
    DEPENDENCY_FAILURE = "dependency_failure"
    INFRASTRUCTURE_ISSUE = "infrastructure_issue"
    NETWORK_ISSUE = "network_issue"
    SCALING_ISSUE = "scaling_issue"
    SECURITY_ISSUE = "security_issue"
    EXTERNAL_DEPENDENCY = "external_dependency"
    DATA_ISSUE = "data_issue"
    UNKNOWN = "unknown"


class HypothesisSource(str, Enum):
    RULES_ENGINE = "rules_engine"
    LLM = "llm"
    HYBRID = "hybrid"
    MANUAL = "manual"
    GNN = "gnn"  # new: learned scorer


class Hypothesis(BaseModel):
    id: UUID = Field(default_factory=uuid4)
    incident_id: UUID

    category: HypothesisCategory
    title: str = Field(max_length=500)
    description: str = ""

    confidence: float = Field(ge=0.0, le=1.0)
    rank: int = Field(default=0, ge=0)
    final_score: float = 0.0

    supporting_evidence_ids: list[UUID] = Field(default_factory=list)
    contradicting_evidence_ids: list[UUID] = Field(default_factory=list)

    # Scoring breakdown (reference hypothesis.py:69-72)
    support_count: int = 0
    recency_weight: float = 0.0
    scope_weight: float = 0.0
    signal_strength: float = 0.0

    recommended_actions: list[str] = Field(default_factory=list)

    why_not_notes: Optional[str] = None
    reasoning: Optional[str] = None

    rule_id: Optional[str] = None
    backend: str = "cpu"

    generated_at: datetime = Field(default_factory=utcnow)
    generated_by: HypothesisSource = HypothesisSource.RULES_ENGINE


class DiagnosisRule(BaseModel):
    """Schema for a deterministic diagnosis rule (reference hypothesis.py:117)."""
    id: str
    name: str
    description: Optional[str] = None
    conditions: list[dict] = Field(default_factory=list)
    hypothesis_template: str = ""
    category: HypothesisCategory = HypothesisCategory.UNKNOWN
    confidence_base: float = Field(default=0.5, ge=0.0, le=1.0)
    recommended_actions: list[str] = Field(default_factory=list)
    priority: int = 50
    enabled: bool = True


class RCAResult(BaseModel):
    incident_id: UUID
    hypotheses: list[Hypothesis] = Field(default_factory=list)
    top_hypothesis: Optional[Hypothesis] = None
    evidence_summary: str = ""
    analysis_duration_seconds: float = 0.0
    rules_matched: list[str] = Field(default_factory=list)
    llm_used: bool = False
    backend: str = "cpu"
    generated_at: datetime = Field(default_factory=utcnow)


class HypothesisFeedback(BaseModel):
    hypothesis_id: UUID
    was_correct: bool
    actual_root_cause: Optional[str] = None
    feedback_notes: Optional[str] = None
    submitted_by: str = "unknown"
    submitted_at: datetime = Field(default_factory=utcnow)
