"""Remediation action, verification and blast-radius models.

Capability parity with the reference (src/models/action.py:12-263): same
14 action types, risk levels, 9-state status lifecycle, idempotency key,
blast-radius scoring fields, and approval request/response schemas.
"""
from __future__ import annotations

from datetime import datetime
from enum import Enum
from typing import Any, Optional
from uuid import UUID, uuid4

from pydantic import BaseModel, Field

from .incident import utcnow


class ActionType(str, Enum):
    RESTART_POD = "restart_pod"
    DELETE_POD = "delete_pod"
    RESTART_DEPLOYMENT = "restart_deployment"
    ROLLBACK_DEPLOYMENT = "rollback_deployment"
    SCALE_REPLICAS = "scale_replicas"
    CORDON_NODE = "cordon_node"
    DRAIN_NODE = "drain_node"
    UNCORDON_NODE = "uncordon_node"
    UPDATE_CONFIGMAP = "update_configmap"
    UPDATE_RESOURCE_LIMITS = "update_resource_limits"
    UPDATE_HPA = "update_hpa"
    RESTART_SERVICE = "restart_service"
    ESCALATE_TO_HUMAN = "escalate_to_human"
    CREATE_TICKET = "create_ticket"


class ActionRisk(str, Enum):
    LOW = "low"
    MEDIUM = "medium"
    HIGH = "high"
    CRITICAL = "critical"


class ActionStatus(str, Enum):
    PROPOSED = "proposed"
    PENDING_APPROVAL = "pending_approval"
    APPROVED = "approved"
    REJECTED = "rejected"
    EXECUTING = "executing"
    COMPLETED = "completed"
    FAILED = "failed"
    ROLLED_BACK = "rolled_back"
    SKIPPED = "skipped"


class Environment(str, Enum):
    DEV = "dev"
    STAGING = "staging"
    UAT = "uat"
    PROD = "prod"


class RemediationAction(BaseModel):
    id: UUID = Field(default_factory=uuid4)
    incident_id: UUID
    hypothesis_id: Optional[UUID] = None

    idempotency_key: str

    action_type: ActionType
    target_resource: str
    target_namespace: str = "default"
    target_cluster: Optional[str] = None

    parameters: dict[str, Any] = Field(default_factory=dict)

    risk_level: ActionRisk = ActionRisk.LOW
    blast_radius_score: float = Field(default=0.0, ge=0.0, le=100.0)
    affected_replicas: int = 0
    environment: Environment = Environment.DEV

    status: ActionStatus = ActionStatus.PROPOSED
    status_reason: Optional[str] = None

    requires_approval: bool = True
    approved_by: Optional[str] = None
    approved_at: Optional[datetime] = None
    rejected_by: Optional[str] = None
    rejected_at: Optional[datetime] = None
    rejection_reason: Optional[str] = None

    executed_at: Optional[datetime] = None
    completed_at: Optional[datetime] = None
    execution_result: Optional[dict[str, Any]] = None
    error_message: Optional[str] = None

    can_rollback: bool = False
    rollback_action_id: Optional[UUID] = None

    created_at: datetime = Field(default_factory=utcnow)
    created_by: str = "system"


class VerificationResult(BaseModel):
    id: UUID = Field(default_factory=uuid4)
    action_id: UUID
    incident_id: UUID

    success: bool
    metrics_improved: bool

    error_rate_before: Optional[float] = None
    error_rate_after: Optional[float] = None
    latency_p99_before: Optional[float] = None
    latency_p99_after: Optional[float] = None
    restart_count_before: Optional[int] = None
    restart_count_after: Optional[int] = None

    pods_healthy_before: Optional[int] = None
    pods_healthy_after: Optional[int] = None

    verification_details: dict[str, Any] = Field(default_factory=dict)
    verification_notes: Optional[str] = None

    verification_started_at: datetime = Field(default_factory=utcnow)
    verified_at: datetime = Field(default_factory=utcnow)
    wait_duration_seconds: int = 0


class BlastRadiusAssessment(BaseModel):
    action_type: ActionType = ActionType.ESCALATE_TO_HUMAN
    target_resource: str = ""
    target_namespace: str = "default"
    environment: Environment = Environment.DEV

    affected_pods: int = 0
    affected_services: int = 0
    affected_deployments: int = 0
    affected_users_estimate: Optional[int] = None

    base_score: float = 0.0
    environment_multiplier: float = 1.0
    criticality_multiplier: float = 1.0
    final_score: float = 0.0

    is_acceptable: bool = True
    requires_approval: bool = False
    risk_level: ActionRisk = ActionRisk.LOW
    warnings: list[str] = Field(default_factory=list)


class ApprovalRequest(BaseModel):
    action_id: UUID
    incident_id: UUID
    incident_title: str
    action_type: ActionType
    target_resource: str
    target_namespace: str
    risk_level: ActionRisk
    blast_radius_score: float
    hypothesis_summary: str = ""
    evidence_summary: str = ""
    recommended_by: str = "kaeg-tpu"
    approval_deadline: Optional[datetime] = None


class ApprovalResponse(BaseModel):
    action_id: UUID
    approved: bool
    responder: str = "system"
    responded_at: datetime = Field(default_factory=utcnow)
    notes: Optional[str] = None
