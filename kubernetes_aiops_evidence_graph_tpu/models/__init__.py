"""Public data-model surface (parity with reference src/models/__init__.py:45-84)."""
from .incident import (
    Incident,
    IncidentCreate,
    IncidentSource,
    IncidentStatus,
    IncidentSummary,
    IncidentUpdate,
    Severity,
    utcnow,
)
from .evidence import (
    CollectorResult,
    DeploymentChange,
    Evidence,
    EvidenceSource,
    EvidenceType,
    GraphEntity,
    GraphRelation,
    LogEvidence,
    MetricDataPoint,
    MetricEvidence,
)
from .hypothesis import (
    DiagnosisRule,
    Hypothesis,
    HypothesisCategory,
    HypothesisFeedback,
    HypothesisSource,
    RCAResult,
)
from .action import (
    ActionRisk,
    ActionStatus,
    ActionType,
    ApprovalRequest,
    ApprovalResponse,
    BlastRadiusAssessment,
    Environment,
    RemediationAction,
    VerificationResult,
)
from .runbook import Runbook, RunbookStep

__all__ = [
    "Incident", "IncidentCreate", "IncidentUpdate", "IncidentSummary",
    "IncidentSource", "IncidentStatus", "Severity", "utcnow",
    "Evidence", "EvidenceType", "EvidenceSource", "GraphEntity",
    "GraphRelation", "CollectorResult", "MetricDataPoint", "MetricEvidence",
    "LogEvidence", "DeploymentChange",
    "Hypothesis", "HypothesisCategory", "HypothesisSource", "DiagnosisRule",
    "RCAResult", "HypothesisFeedback",
    "RemediationAction", "ActionType", "ActionRisk", "ActionStatus",
    "Environment", "VerificationResult", "BlastRadiusAssessment",
    "ApprovalRequest", "ApprovalResponse",
    "Runbook", "RunbookStep",
]
