"""Runbook model (reference persists runbooks as a Postgres row,
src/services/runbook/generator.py:273-293 + scripts/init-db.sql runbooks
table)."""
from __future__ import annotations

from datetime import datetime
from typing import Any
from uuid import UUID, uuid4

from pydantic import BaseModel, Field

from .incident import utcnow


class RunbookStep(BaseModel):
    order: int
    title: str
    description: str = ""
    commands: list[str] = Field(default_factory=list)


class Runbook(BaseModel):
    id: UUID = Field(default_factory=uuid4)
    incident_id: UUID
    hypothesis_id: UUID | None = None
    title: str
    summary: str = ""
    steps: list[RunbookStep] = Field(default_factory=list)
    kubectl_commands: list[str] = Field(default_factory=list)
    investigation_queries: list[str] = Field(default_factory=list)
    dashboard_links: dict[str, str] = Field(default_factory=dict)
    metadata: dict[str, Any] = Field(default_factory=dict)
    generated_at: datetime = Field(default_factory=utcnow)
