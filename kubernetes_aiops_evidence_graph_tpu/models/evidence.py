"""Evidence, graph-entity and collector-result models.

Capability parity with the reference (src/models/evidence.py:12-200): the
same 16 evidence types, 7 sources (plus a new ``simulator`` source), Evidence payload shape (``data`` dict +
``signal_strength`` in [0,1]), and the GraphEntity/GraphRelation node/edge
schema — here feeding an in-memory tensorized graph instead of Neo4j.
"""
from __future__ import annotations

from datetime import datetime
from enum import Enum
from typing import Any, Optional
from uuid import UUID, uuid4

from pydantic import BaseModel, Field

from .incident import utcnow


class EvidenceType(str, Enum):
    KUBERNETES_POD = "kubernetes_pod"
    KUBERNETES_DEPLOYMENT = "kubernetes_deployment"
    KUBERNETES_REPLICASET = "kubernetes_replicaset"
    KUBERNETES_EVENT = "kubernetes_event"
    KUBERNETES_NODE = "kubernetes_node"
    KUBERNETES_SERVICE = "kubernetes_service"
    KUBERNETES_CONFIGMAP = "kubernetes_configmap"
    KUBERNETES_HPA = "kubernetes_hpa"
    KUBERNETES_PVC = "kubernetes_pvc"
    LOG_SIGNAL = "log_signal"
    METRIC_SIGNAL = "metric_signal"
    DEPLOY_CHANGE = "deploy_change"
    CONFIG_CHANGE = "config_change"
    IMAGE_CHANGE = "image_change"
    DEPENDENCY_STATE = "dependency_state"
    NETWORK_TOPOLOGY = "network_topology"


class EvidenceSource(str, Enum):
    KUBERNETES_API = "kubernetes_api"
    PROMETHEUS = "prometheus"
    LOKI = "loki"
    ARGOCD = "argocd"
    HELM = "helm"
    GIT = "git"
    KUBE_STATE_METRICS = "kube_state_metrics"
    SIMULATOR = "simulator"  # new: hermetic replay backend


class Evidence(BaseModel):
    id: UUID = Field(default_factory=uuid4)
    incident_id: UUID
    evidence_type: EvidenceType
    source: EvidenceSource

    entity_name: str
    entity_namespace: str = "default"
    entity_uid: Optional[str] = None

    data: dict[str, Any] = Field(default_factory=dict)
    summary: Optional[str] = None

    signal_strength: float = Field(default=0.5, ge=0.0, le=1.0)
    is_anomaly: bool = False

    collected_at: datetime = Field(default_factory=utcnow)
    time_window_start: Optional[datetime] = None
    time_window_end: Optional[datetime] = None


class GraphEntity(BaseModel):
    """A node in the evidence graph (reference: Neo4j node, evidence.py:113)."""
    id: str
    type: str  # Incident|Pod|Deployment|Node|Service|HPA|ConfigMap|ChangeEvent|...
    properties: dict[str, Any] = Field(default_factory=dict)


class GraphRelation(BaseModel):
    """An edge in the evidence graph (reference: evidence.py:134)."""
    source_id: str
    target_id: str
    relation_type: str  # AFFECTS|SCHEDULED_ON|OWNS|SELECTS|CALLS|HAS_RECENT_CHANGE|CORRELATES_WITH
    properties: dict[str, Any] = Field(default_factory=dict)


class CollectorResult(BaseModel):
    """Bundle returned by one collector run (reference: evidence.py:152)."""
    collector_name: str
    success: bool = True
    evidence: list[Evidence] = Field(default_factory=list)
    entities: list[GraphEntity] = Field(default_factory=list)
    relations: list[GraphRelation] = Field(default_factory=list)
    errors: list[str] = Field(default_factory=list)
    duration_seconds: float = 0.0


class MetricDataPoint(BaseModel):
    timestamp: datetime
    value: float
    labels: dict[str, str] = Field(default_factory=dict)


class MetricEvidence(BaseModel):
    query: str
    metric_name: str
    data_points: list[MetricDataPoint] = Field(default_factory=list)
    current_value: Optional[float] = None
    threshold: Optional[float] = None
    is_above_threshold: bool = False


class LogEvidence(BaseModel):
    pod_name: str
    container_name: str = ""
    log_lines: list[dict[str, Any]] = Field(default_factory=list)
    error_count: int = 0
    warning_count: int = 0
    patterns_found: list[str] = Field(default_factory=list)
    stack_traces: list[str] = Field(default_factory=list)


class DeploymentChange(BaseModel):
    deployment_name: str
    namespace: str
    change_type: str  # image_update|config_change|scale|rollback
    old_value: Optional[str] = None
    new_value: Optional[str] = None
    changed_at: datetime
    changed_by: Optional[str] = None
    revision: int = 0
