"""Incident model — the central entity linking evidence, hypotheses, actions.

Capability parity with the reference (src/models/incident.py:12-132):
same severity/status/source vocabularies and K8s context fields, so alert
payloads and persisted rows are interchangeable between the two systems.
"""
from __future__ import annotations

from datetime import datetime, timezone
from enum import Enum
from typing import Optional
from uuid import UUID, uuid4

from pydantic import BaseModel, Field

from ..utils.timeutils import utcnow


class Severity(str, Enum):
    CRITICAL = "critical"
    HIGH = "high"
    MEDIUM = "medium"
    LOW = "low"
    INFO = "info"


class IncidentStatus(str, Enum):
    OPEN = "open"
    INVESTIGATING = "investigating"
    IDENTIFIED = "identified"
    REMEDIATING = "remediating"
    RESOLVED = "resolved"
    CLOSED = "closed"


class IncidentSource(str, Enum):
    ALERTMANAGER = "alertmanager"
    GRAFANA = "grafana"
    PROMETHEUS = "prometheus"
    MANUAL = "manual"
    SYNTHETIC = "synthetic"


class Incident(BaseModel):
    id: UUID = Field(default_factory=uuid4)
    fingerprint: str
    title: str = Field(max_length=500)
    description: Optional[str] = None
    severity: Severity = Severity.MEDIUM
    status: IncidentStatus = IncidentStatus.OPEN
    source: IncidentSource = IncidentSource.MANUAL

    # Kubernetes context
    cluster: str = "local"
    namespace: str = "default"
    service: Optional[str] = None

    labels: dict[str, str] = Field(default_factory=dict)
    annotations: dict[str, str] = Field(default_factory=dict)

    started_at: datetime = Field(default_factory=utcnow)
    acknowledged_at: Optional[datetime] = None
    resolved_at: Optional[datetime] = None
    created_at: datetime = Field(default_factory=utcnow)
    updated_at: datetime = Field(default_factory=utcnow)


class IncidentCreate(BaseModel):
    fingerprint: str
    title: str
    description: Optional[str] = None
    severity: Severity
    source: IncidentSource
    cluster: str = "local"
    namespace: str = "default"
    service: Optional[str] = None
    labels: dict[str, str] = Field(default_factory=dict)
    annotations: dict[str, str] = Field(default_factory=dict)
    started_at: datetime = Field(default_factory=utcnow)


class IncidentUpdate(BaseModel):
    title: Optional[str] = None
    description: Optional[str] = None
    severity: Optional[Severity] = None
    status: Optional[IncidentStatus] = None
    acknowledged_at: Optional[datetime] = None
    resolved_at: Optional[datetime] = None


class IncidentSummary(BaseModel):
    id: UUID
    fingerprint: str
    title: str
    severity: Severity
    status: IncidentStatus
    cluster: str
    namespace: str
    service: Optional[str] = None
    started_at: datetime
