# kaeg-tpu runtime image.
#
# Parity with the reference Dockerfile (reference Dockerfile:1-36) minus its
# defects: the served module actually exists (reference CMD pointed at a
# missing src/main.py, SURVEY.md §3.6 item 1) and no nonexistent tests/ COPY.
# The TPU runtime (libtpu + jax[tpu]) is provided by the host image on TPU
# VMs; this image carries the CPU fallback so the ingestion edge and CPU RCA
# backend run anywhere.
FROM python:3.11-slim

WORKDIR /app

RUN apt-get update && apt-get install -y --no-install-recommends \
        g++ curl ca-certificates \
    && rm -rf /var/lib/apt/lists/* \
    && curl -fsSLo /usr/local/bin/kubectl \
        "https://dl.k8s.io/release/v1.29.0/bin/linux/amd64/kubectl" \
    && chmod +x /usr/local/bin/kubectl

COPY pyproject.toml ./
RUN pip install --no-cache-dir "jax[cpu]" flax optax numpy pyyaml pydantic

COPY kubernetes_aiops_evidence_graph_tpu/ ./kubernetes_aiops_evidence_graph_tpu/
COPY native/ ./native/
COPY tests/ ./tests/

ENV PYTHONUNBUFFERED=1
EXPOSE 8000

# default: serve the platform (API + worker in one process); the compose
# file overrides the command for the worker-only role
CMD ["python", "-m", "kubernetes_aiops_evidence_graph_tpu.serve"]
