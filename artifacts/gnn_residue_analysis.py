"""Reproduce and characterize the GNN holdout residue (VERDICT r4 item 4).

Rebuilds the BASELINE holdout (episodes 100-129 of the 130-episode
product-scale run), finds every GNN miss under the shipped checkpoint, and
for each miss asks the deterministic rules oracle the same question on the
same snapshot: if the oracle also scores the confused pair equally (or
picks the same wrong rule), the miss is label-ambiguous by construction;
if the oracle is right, the GNN has a feature/capacity gap.

Writes artifacts/gnn_residue.json.
"""
from __future__ import annotations

import json
import os
import sys

os.environ.setdefault("XLA_FLAGS", "--xla_force_host_platform_device_count=1")
import jax

jax.config.update("jax_platforms", "cpu")

import numpy as np

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

from kubernetes_aiops_evidence_graph_tpu.rca import gnn, get_backend
from kubernetes_aiops_evidence_graph_tpu.rca.gnn_backend import GnnRcaBackend
from kubernetes_aiops_evidence_graph_tpu.rca.ruleset import RULES
from kubernetes_aiops_evidence_graph_tpu.rca.train import make_episode

RULE_IDS = [r.id for r in RULES]
SIZES = [96, 256, 512, 1024, 2048]


def main() -> None:
    params = GnnRcaBackend().params
    fwd = jax.jit(gnn.forward)
    backend = get_backend("tpu")

    misses = []
    total = correct = 0
    for e in range(100, 130):
        b = make_episode(SIZES[e % len(SIZES)], 8, seed=e,
                         return_snapshot=True)
        snap = b["snapshot"]
        logits = np.asarray(fwd(
            params, b["features"], b["node_kind"], b["node_mask"],
            b["edge_src"], b["edge_dst"], b["edge_rel"], b["edge_mask"],
            b["incident_nodes"]))
        probs = np.exp(logits - logits.max(-1, keepdims=True))
        probs = probs / probs.sum(-1, keepdims=True)
        mask = np.asarray(b["label_mask"]) > 0
        y = np.asarray(b["labels"])
        pred = logits.argmax(-1)
        # rules oracle on the same snapshot
        oracle = backend.score_snapshot(snap) if snap is not None else None
        for i in np.nonzero(mask)[0]:
            total += 1
            if pred[i] == y[i]:
                correct += 1
                continue
            p_sorted = np.argsort(probs[i])[::-1]
            rec = {
                "episode": int(e), "incident_row": int(i),
                "true_rule": RULE_IDS[y[i]],
                "gnn_pred": RULE_IDS[pred[i]] if pred[i] < len(RULE_IDS)
                else "unknown",
                "gnn_top2": [[RULE_IDS[j] if j < len(RULE_IDS) else "unknown",
                              float(probs[i][j])] for j in p_sorted[:2]],
            }
            if oracle is not None:
                oi = int(oracle["top_rule_index"][i])
                rec["oracle_pred"] = (RULE_IDS[oi] if 0 <= oi < len(RULE_IDS)
                                      else "unknown")
                srow = np.asarray(oracle["scores"][i], dtype=float)
                order = np.argsort(srow)[::-1]
                rec["oracle_top2"] = [[RULE_IDS[j], float(srow[j])]
                                      for j in order[:2]]
                rec["oracle_margin"] = float(srow[order[0]] - srow[order[1]])
            misses.append(rec)
    out = {"holdout_incidents": total, "correct": correct,
           "accuracy": correct / max(total, 1), "misses": misses}
    path = os.path.join(os.path.dirname(__file__), "gnn_residue.json")
    with open(path, "w") as f:
        json.dump(out, f, indent=1)
    print(json.dumps({k: v for k, v in out.items() if k != "misses"}))
    for m in misses:
        print(json.dumps(m))


if __name__ == "__main__":
    main()
